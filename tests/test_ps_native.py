"""Native zero-Python PS read path + exact multi-call wait fan-in.

Covers the ISSUE-6 tentpole end to end: byte-for-byte parity of the
native Lookup handler against the Python ``_serve`` path (randomized /
empty / full-shard batches), proof that no Python runs in the native
read loop, torn-row stress where native reads race Python ``ApplyGrad``
generation installs (RACECHECK clean), ``rpc.CallGroup`` semantics, and
the hedge's exact-wakeup contract (``rpc_hedge_waits`` counts
completions, not 2ms polling slices)."""

import struct
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience
from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

VOCAB, DIM, SHARDS = 64, 16, 4


def _lookup_req(ids: np.ndarray) -> bytes:
    return struct.pack("<i", ids.size) + np.asarray(
        ids, np.int32).tobytes()


# ---- parity: native Lookup vs the Python _serve path ----

@pytest.mark.needs_native
def test_native_lookup_parity_with_python_serve():
    server = PsShardServer(VOCAB, DIM, 1, SHARDS, native_read=True)
    from brpc_tpu import rpc

    ch = rpc.Channel(server.address)
    rows_per = VOCAB // SHARDS
    rng = np.random.default_rng(11)
    batches = [
        rng.integers(server.base, server.base + rows_per,
                     37).astype(np.int32),              # randomized
        np.empty(0, np.int32),                          # empty batch
        np.arange(server.base, server.base + rows_per,
                  dtype=np.int32),                      # full shard
        np.array([server.base] * 5, np.int32),          # duplicates
    ]
    try:
        for ids in batches:
            req = _lookup_req(ids)
            native = ch.call("Ps", "Lookup", req)
            python = server._serve("Lookup", req)
            if isinstance(python, rpc.IOBuf):   # zero-copy return
                with python:
                    python = python.tobytes()
            assert native == python  # byte-for-byte
        assert server.native_lookups == len(batches)
    finally:
        ch.close()
        server.close()


@pytest.mark.needs_native
def test_native_lookup_matches_python_twin_server():
    """Same seed => same table: a native_read server and a plain Python
    server must serve identical bytes for identical requests."""
    from brpc_tpu import rpc

    nat = PsShardServer(VOCAB, DIM, 0, 1, seed=5, native_read=True)
    py = PsShardServer(VOCAB, DIM, 0, 1, seed=5)
    ch_n = rpc.Channel(nat.address)
    ch_p = rpc.Channel(py.address)
    try:
        rng = np.random.default_rng(2)
        for _ in range(5):
            ids = rng.integers(0, VOCAB, 23).astype(np.int32)
            req = _lookup_req(ids)
            assert ch_n.call("Ps", "Lookup", req) == \
                ch_p.call("Ps", "Lookup", req)
        assert nat.native_lookups == 5
        assert py.native_lookups == 0
    finally:
        ch_n.close()
        ch_p.close()
        nat.close()
        py.close()


@pytest.mark.needs_native
def test_native_lookup_runs_with_zero_python_in_the_loop():
    """Break the Python serving path entirely: native Lookups keep
    working (nothing in the loop to break), while ApplyGrad — still
    owned by Python — fails through the broken handler."""
    from brpc_tpu import rpc

    server = PsShardServer(VOCAB, DIM, 0, 1, native_read=True)
    server._serve = None  # the Python path would now TypeError
    ch = rpc.Channel(server.address)
    ids = np.arange(8, dtype=np.int32)
    try:
        rsp = ch.call("Ps", "Lookup", _lookup_req(ids))
        assert len(rsp) == 8 * DIM * 4
        with pytest.raises(rpc.RpcError):
            ch.call("Ps", "ApplyGrad",
                    _lookup_req(ids) + b"\0" * (8 * DIM * 4))
    finally:
        ch.close()
        server.close()


@pytest.mark.needs_native
def test_native_lookup_rejects_out_of_shard_ids():
    from brpc_tpu import rpc

    server = PsShardServer(VOCAB, DIM, 1, SHARDS, native_read=True)
    ch = rpc.Channel(server.address)
    try:
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "Lookup",
                    _lookup_req(np.array([0], np.int32)))  # shard 0's row
        assert "outside shard" in str(ei.value)
        # malformed framing fails cleanly too (no native OOB read)
        with pytest.raises(rpc.RpcError):
            ch.call("Ps", "Lookup", struct.pack("<i", 99) + b"\x01\x02")
    finally:
        ch.close()
        server.close()


@pytest.mark.needs_native
def test_install_publishes_new_generation_to_native_readers():
    from brpc_tpu import rpc

    server = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, native_read=True)
    ch = rpc.Channel(server.address)
    ids = np.array([3], np.int32)
    try:
        before = np.frombuffer(ch.call("Ps", "Lookup", _lookup_req(ids)),
                               np.float32).copy()
        grads = np.ones((1, DIM), np.float32)
        ch.call("Ps", "ApplyGrad",
                _lookup_req(ids) + grads.tobytes())  # Python write path
        after = np.frombuffer(ch.call("Ps", "Lookup", _lookup_req(ids)),
                              np.float32)
        np.testing.assert_allclose(after, before - 1.0, atol=1e-6)
        assert server._shard.generation == 1
    finally:
        ch.close()
        server.close()


# ---- torn-row stress: native reads race Python ApplyGrad installs ----

def _row_deltas_are_whole(rows, init_rows):
    d = rows - init_rows
    return np.allclose(d.max(axis=-1), d.min(axis=-1), atol=1e-5)


@pytest.mark.needs_native
def test_native_read_no_torn_rows_under_write_race_racecheck_clean():
    """call_async fan-outs of native Lookups racing Python ApplyGrad
    generation installs: every served row is a whole snapshot, no update
    is lost, and RACECHECK reports no lock held across a blocking call
    on the serving path."""
    from brpc_tpu import rpc
    from brpc_tpu.analysis import race

    vocab, dim = 64, 32
    race.clear()
    race.set_enabled(True)
    try:
        server = PsShardServer(vocab, dim, 0, 1, lr=0.25,
                               native_read=True)
        ch = rpc.Channel(server.address, timeout_ms=30000)
        try:
            init = server.table.copy()
            all_ids = np.arange(vocab, dtype=np.int32)
            grad = np.ones((vocab, dim), np.float32)
            req_ids = _lookup_req(all_ids)
            req_grad = req_ids + grad.tobytes()
            rounds, lookups, applies = 25, 8, 2
            for _ in range(rounds):
                pending = [ch.call_async("Ps", "Lookup", req_ids)
                           for _ in range(lookups)]
                pending += [ch.call_async("Ps", "ApplyGrad", req_grad)
                            for _ in range(applies)]
                for i, call in enumerate(pending):
                    rsp = call.join()
                    if i < lookups:
                        rows = np.frombuffer(rsp, np.float32).reshape(
                            vocab, dim)
                        assert _row_deltas_are_whole(rows, init)
            # write lock lost no update: rounds x applies all-ones grads
            # at lr=0.25 move every element by exactly -12.5, and the
            # NATIVE read path serves the final generation
            final = np.frombuffer(
                ch.call("Ps", "Lookup", req_ids),
                np.float32).reshape(vocab, dim)
            np.testing.assert_allclose(final, init - 12.5, atol=1e-4)
            assert server.native_lookups == rounds * lookups + 1
        finally:
            ch.close()
            server.close()
        blocked = [f for f in race.findings()
                   if f.kind == "blocking-call" and "ps.shard" in f.locks]
        assert blocked == [], race.report()
    finally:
        race.set_enabled(None)
        race.clear()


@pytest.mark.needs_native
def test_remote_embedding_parity_native_vs_python_cluster():
    nat = [PsShardServer(VOCAB, DIM, i, SHARDS, native_read=True)
           for i in range(SHARDS)]
    py = [PsShardServer(VOCAB, DIM, i, SHARDS) for i in range(SHARDS)]
    emb_n = RemoteEmbedding([s.address for s in nat], VOCAB, DIM)
    emb_p = RemoteEmbedding([s.address for s in py], VOCAB, DIM)
    try:
        rng = np.random.default_rng(7)
        ids = rng.integers(0, VOCAB, size=(5, 6)).astype(np.int32)
        np.testing.assert_array_equal(emb_n.lookup(ids), emb_p.lookup(ids))
        grads = rng.standard_normal((5, 6, DIM)).astype(np.float32)
        emb_n.apply_gradients(ids, grads)
        emb_p.apply_gradients(ids, grads)
        np.testing.assert_array_equal(emb_n.lookup(ids), emb_p.lookup(ids))
        assert sum(s.native_lookups for s in nat) > 0
    finally:
        emb_n.close()
        emb_p.close()
        for s in nat + py:
            s.close()


# ---- call groups: exact multi-call fan-in ----

@pytest.fixture
def echo_server():
    from brpc_tpu import rpc

    srv = rpc.Server()
    srv.add_service("Echo", lambda method, req: b"e:" + req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    try:
        yield srv, ch
    finally:
        fault.clear()
        ch.close()
        srv.close()


@pytest.mark.needs_native
def test_call_group_wait_all(echo_server):
    from brpc_tpu import rpc

    _, ch = echo_server
    calls = [ch.call_async("Echo", "Hi", bytes([i])) for i in range(6)]
    group = rpc.CallGroup()
    for pc in calls:
        group.add(pc)
    assert group.wait(5.0)
    assert group.completed == 6
    # every join is now a non-blocking collection
    assert [pc.join() for pc in calls] == \
        [b"e:" + bytes([i]) for i in range(6)]
    assert group.wait(0.0)  # level-triggered
    group.close()


@pytest.mark.needs_native
def test_call_group_wait_any_consumes_one_per_completion(echo_server):
    from brpc_tpu import rpc

    _, ch = echo_server
    calls = [ch.call_async("Echo", "Hi", b"x") for _ in range(3)]
    group = rpc.CallGroup()
    for pc in calls:
        group.add(pc)
    # exactly N successful wait_any returns for N calls
    for _ in range(3):
        assert group.wait_any(5.0)
    assert not group.wait_any(0.05)  # all consumed -> times out
    for pc in calls:
        pc.join()
    group.close()


@pytest.mark.needs_native
def test_call_group_completed_call_counts_immediately(echo_server):
    from brpc_tpu import rpc

    _, ch = echo_server
    pc = ch.call_async("Echo", "Hi", b"y")
    assert pc.wait(5.0)              # completes BEFORE registration
    group = rpc.CallGroup()
    group.add(pc)
    assert group.wait(0.0)
    assert group.wait_any(0.0)
    pc.join()
    group.close()


@pytest.mark.needs_native
def test_call_group_timeout_and_inflight_close(echo_server):
    from brpc_tpu import rpc

    _, ch = echo_server
    fault.install(fault.FaultPlan([
        fault.FaultRule(action="delay", side="server", service="Echo",
                        delay_ms=300)]))
    pc = ch.call_async("Echo", "Hi", b"z")
    group = rpc.CallGroup()
    group.add(pc)
    assert not group.wait(0.02)       # times out while in flight
    group.close()                     # safe with the call still pending
    assert pc.join() == b"e:z"


# ---- hedge exactness: group wait, not polling slices ----

@pytest.mark.needs_native
def test_backup_call_wakes_exactly_not_in_slices(echo_server):
    """The hedge loop consumes at most one wakeup per attempt completion
    (rpc_hedge_waits), independent of how long the slow primary takes —
    the pre-group implementation polled brt_call_wait in 2ms slices,
    which for a 400ms straggler would have been ~hundreds of waits."""
    _, ch = echo_server
    obs.set_enabled(True)
    obs.reset_fabric_vars()
    fault.install(fault.FaultPlan([
        fault.FaultRule(action="delay", side="server", service="Echo",
                        delay_ms=400, max_hits=1)]))
    t0 = time.monotonic()
    out = resilience.backup_call(ch, "Echo", "Hi", b"h", backup_ms=20)
    dt_ms = (time.monotonic() - t0) * 1000
    assert out == b"e:h"
    assert dt_ms < 300                 # hedge bounded the latency
    assert obs.counter("rpc_backup_fired").get_value() == 1
    waits = obs.counter("rpc_hedge_waits").get_value()
    assert 1 <= waits <= 2             # one per consumed completion
    obs.reset_fabric_vars()
    obs.set_enabled(False)


@pytest.mark.needs_native
def test_fan_out_uses_group_wait(echo_server):
    """The unhedged PS fan-out collects by completion order over one
    call group — rpc_group_waits moves, and results stay aligned."""
    servers = [PsShardServer(VOCAB, DIM, i, SHARDS) for i in range(SHARDS)]
    emb = RemoteEmbedding([s.address for s in servers], VOCAB, DIM)
    obs.set_enabled(True)
    obs.reset_fabric_vars()
    try:
        ids = np.arange(VOCAB, dtype=np.int32)  # touches every shard
        out = emb.lookup(ids)
        assert out.shape == (VOCAB, DIM)
        assert obs.counter("rpc_group_waits").get_value() >= SHARDS
    finally:
        obs.reset_fabric_vars()
        obs.set_enabled(False)
        emb.close()
        for s in servers:
            s.close()


# ---- native latency export: SchemeInfo p99 sees the zero-Python path ----

@pytest.mark.needs_native
def test_native_lookup_latency_reaches_scheme_info_p99_and_policy():
    """Zero-Python Lookups never cross the Python latency recorder; the
    server drains the native sum/count pair (PsShard.lookup_stats) into
    it on SchemeInfo, so per-server p99 — and with it RebalancePolicy's
    tail-pressure input — sees native-served traffic.  The fold is
    delta-based: a second SchemeInfo with no new traffic adds nothing."""
    import json

    from brpc_tpu import rpc
    from brpc_tpu.rebalance import RebalanceOptions, RebalancePolicy

    vocab, dim, n_lookups = 1 << 15, 32, 6  # 4MB rsp: µs-visible work
    server = PsShardServer(vocab, dim, 0, 1, native_read=True)
    ch = rpc.Channel(server.address, timeout_ms=30000)
    try:
        req = _lookup_req(np.arange(vocab, dtype=np.int32))
        for _ in range(n_lookups):
            assert len(ch.call("Ps", "Lookup", req)) == vocab * dim * 4
        assert server.native_lookups == n_lookups
        sum_us, count = server._shard.lookup_stats()
        assert count == n_lookups and sum_us > 0
        assert server._lat.count == 0  # nothing crossed Python yet

        p99_us = json.loads(ch.call("Ps", "SchemeInfo", b""))["p99_us"]
        assert p99_us > 0.0
        assert server._lat.count == n_lookups
        json.loads(ch.call("Ps", "SchemeInfo", b""))
        assert server._lat.count == n_lookups  # no double count

        # close the loop: the measured p99 (in ms) sustained over a
        # lower threshold splits with zero qps signal
        t = [0.0]
        pol = RebalancePolicy(RebalanceOptions(
            split_qps=1e9, merge_qps=1.0, sustain_s=1.0,
            min_interval_s=5.0, max_shards=8,
            split_p99_ms=p99_us / 1000.0 / 2.0), clock=lambda: t[0])
        p99_ms = [p99_us / 1000.0]
        assert pol.decide(1, [0.0], shard_p99_ms=p99_ms) is None
        t[0] += 1.1
        d = pol.decide(1, [0.0], shard_p99_ms=p99_ms)
        assert d is not None and d.kind == "split"
        assert "tail pressure" in d.reason
    finally:
        ch.close()
        server.close()
