"""Self-driving elasticity: the rebalancer policy (fake clock, no
servers) and the daemon end to end (ISSUE 13).

The policy half is the tier-1 bounded coverage the CI satellite asks
for: split/merge/failback decisions, sustain windows, the hysteresis
band, min-interval cooldown and flap-freedom are proven against an
injected clock — no live servers, no wall time.  The daemon half
(native-gated) drives a real failback and a real policy-decided split
through ``Rebalancer.step()``.
"""

import json
import struct
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs
from brpc_tpu.rebalance import (Decision, RebalanceOptions,
                                RebalancePolicy, Rebalancer)


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)
    fault.clear()


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _policy(**kw):
    clock = FakeClock()
    opts = RebalanceOptions(split_qps=100.0, merge_qps=10.0,
                            sustain_s=1.0, min_interval_s=5.0,
                            max_shards=8, **kw)
    return RebalancePolicy(opts, clock=clock), clock


# ---------------------------------------------------------------------------
# the decision function under a fake clock
# ---------------------------------------------------------------------------

def test_options_validate_hysteresis_band():
    with pytest.raises(ValueError):
        RebalanceOptions(split_qps=100.0, merge_qps=80.0)
    with pytest.raises(ValueError):
        RebalanceOptions(min_shards=0)
    RebalanceOptions(split_qps=100.0, merge_qps=50.0)   # exactly half


def test_split_requires_sustain():
    pol, clock = _policy()
    assert pol.decide(2, [150.0, 20.0]) is None        # first sight
    clock.advance(0.5)
    assert pol.decide(2, [150.0, 20.0]) is None        # not yet
    clock.advance(0.6)
    d = pol.decide(2, [150.0, 20.0])                   # sustained
    assert d is not None and d.kind == "split" and d.num_shards == 4


def test_flapping_signal_never_acts():
    pol, clock = _policy()
    for _ in range(20):
        assert pol.decide(2, [150.0, 0.0]) is None     # hot...
        clock.advance(0.6)
        assert pol.decide(2, [5.0, 0.0]) is None       # ...cold: reset
        clock.advance(0.6)


def test_min_interval_cooldown_and_merge_hysteresis():
    pol, clock = _policy()
    clock.advance(1.1)
    pol.decide(2, [150.0, 20.0])
    clock.advance(1.1)
    d = pol.decide(2, [150.0, 20.0])
    assert d.kind == "split"
    pol.note_action()
    # immediately cold on the NEW topology: merge may not fire inside
    # the cooldown, and its sustain only starts counting fresh
    clock.advance(1.2)
    assert pol.decide(4, [1.0, 1.0, 1.0, 1.0]) is None
    clock.advance(1.2)   # sustain satisfied but still in cooldown
    assert pol.decide(4, [1.0, 1.0, 1.0, 1.0]) is None
    clock.advance(3.0)   # cooldown over (5s), sustain long since held
    d = pol.decide(4, [1.0, 1.0, 1.0, 1.0])
    assert d is not None and d.kind == "merge" and d.num_shards == 2
    # a load INSIDE the band (between merge and split) decides nothing
    pol.note_action()
    clock.advance(10.0)
    for _ in range(5):
        assert pol.decide(2, [50.0, 50.0]) is None
        clock.advance(1.0)


def test_split_respects_max_shards_merge_respects_min():
    pol, clock = _policy()
    for _ in range(3):
        clock.advance(1.1)
        assert pol.decide(8, [500.0] * 8) is None      # 16 > max 8
    pol2, clock2 = _policy()
    for _ in range(3):
        clock2.advance(1.1)
        assert pol2.decide(1, [1.0]) is None           # min reached
    # odd shard counts cannot halve
    pol3, clock3 = _policy()
    for _ in range(3):
        clock3.advance(1.1)
        assert pol3.decide(3, [1.0, 1.0, 1.0]) is None


def test_failback_decision_beats_split_and_has_own_sustain():
    pol, clock = _policy()
    mis = [(1, "10.0.0.1:7")]
    assert pol.decide(2, [150.0, 0.0], misplaced=mis) is None
    clock.advance(0.6)                                 # > 0.5s sustain
    d = pol.decide(2, [150.0, 0.0], misplaced=mis)
    assert d is not None and d.kind == "failback"
    assert d.shard == 1 and d.addr == "10.0.0.1:7"
    # a misplacement that heals itself resets the sustain window
    pol2, clock2 = _policy()
    pol2.decide(2, [0.0, 0.0], misplaced=mis)
    clock2.advance(0.3)
    pol2.decide(2, [0.0, 0.0])                         # healed
    clock2.advance(0.3)
    assert pol2.decide(2, [0.0, 0.0], misplaced=mis) is None


def test_failback_can_be_disabled():
    clock = FakeClock()
    pol = RebalancePolicy(RebalanceOptions(failback=False),
                          clock=clock)
    mis = [(0, "10.0.0.1:7")]
    for _ in range(4):
        clock.advance(1.0)
        # rates inside the hysteresis band: the ONLY candidate action
        # would be the failback, and it is disabled
        assert pol.decide(2, [50.0, 50.0], misplaced=mis) is None


# ---------------------------------------------------------------------------
# tail-pressure signals: p99 / shed rate as split triggers (ISSUE 16)
# ---------------------------------------------------------------------------

def test_tail_pressure_p99_splits_without_qps():
    pol, clock = _policy(split_p99_ms=50.0)
    # qps WELL below the split threshold: only the p99 signal is hot
    assert pol.decide(2, [10.0, 5.0],
                      shard_p99_ms=[80.0, 1.0]) is None   # first sight
    clock.advance(1.1)
    d = pol.decide(2, [10.0, 5.0], shard_p99_ms=[80.0, 1.0])
    assert d is not None and d.kind == "split" and d.num_shards == 4
    assert "tail pressure" in d.reason


def test_tail_pressure_shed_rate_splits_without_qps():
    pol, clock = _policy(split_shed_per_s=5.0)
    assert pol.decide(2, [10.0, 5.0],
                      shed_per_s=[20.0, 0.0]) is None
    clock.advance(1.1)
    d = pol.decide(2, [10.0, 5.0], shed_per_s=[20.0, 0.0])
    assert d is not None and d.kind == "split"
    assert "tail pressure" in d.reason


def test_tail_pressure_requires_sustain_like_qps():
    pol, clock = _policy(split_p99_ms=50.0)
    for _ in range(10):
        # flapping p99 never acts: hot sample, then a cold one resets
        assert pol.decide(2, [10.0, 5.0],
                          shard_p99_ms=[80.0, 1.0]) is None
        clock.advance(0.6)
        assert pol.decide(2, [10.0, 5.0],
                          shard_p99_ms=[5.0, 1.0]) is None
        clock.advance(0.6)


def test_tail_pressure_vetoes_merge():
    clock = FakeClock()
    pol = RebalancePolicy(
        RebalanceOptions(split_qps=100.0, merge_qps=10.0, sustain_s=1.0,
                         min_interval_s=5.0, max_shards=4,
                         split_p99_ms=50.0), clock=clock)
    clock.advance(10.0)
    # 4 shards, qps cold enough to merge — but one shard's tail is on
    # fire: shrinking the fleet under pressure would make it worse
    for _ in range(4):
        assert pol.decide(4, [1.0, 1.0, 1.0, 1.0],
                          shard_p99_ms=[80.0, 1.0, 1.0, 1.0]) is None
        clock.advance(1.1)
    # pressure clears: merge sustain starts fresh, then fires
    assert pol.decide(4, [1.0, 1.0, 1.0, 1.0],
                      shard_p99_ms=[5.0, 1.0, 1.0, 1.0]) is None
    clock.advance(1.1)
    d = pol.decide(4, [1.0, 1.0, 1.0, 1.0],
                   shard_p99_ms=[5.0, 1.0, 1.0, 1.0])
    assert d is not None and d.kind == "merge"


def test_tail_pressure_knobs_default_off():
    pol, clock = _policy()                 # both thresholds at 0.0
    for _ in range(4):
        clock.advance(1.1)
        # enormous signals are IGNORED until a threshold is configured
        assert pol.decide(2, [10.0, 5.0], shard_p99_ms=[9999.0, 0.0],
                          shed_per_s=[9999.0, 0.0]) is None


# ---------------------------------------------------------------------------
# the daemon end to end (native)
# ---------------------------------------------------------------------------

VOCAB, DIM = 256, 8


def _registry(rpc):
    srv = rpc.Server()
    srv.add_naming_registry()
    port = srv.start("127.0.0.1:0")
    return srv, f"127.0.0.1:{port}"


@pytest.mark.needs_native
def test_rebalancer_fails_back_revived_primary():
    """A shard whose primary moved to a backup (failure-driven
    promotion) and whose declared primary is back and caught up: the
    rebalancer promotes the declared primary back — clients converge
    exactly as in a failure failover."""
    from brpc_tpu import rpc
    from brpc_tpu.naming import (NamingClient, PartitionScheme,
                                 ReplicaSet, publish_scheme)
    from brpc_tpu.ps_remote import PsShardServer
    reg_server, reg_addr = _registry(rpc)
    servers = [PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
               for _ in range(3)]
    rs = ReplicaSet(tuple(s.address for s in servers), primary=0)
    for i, s in enumerate(servers):
        s.configure_replication(rs, i)
    scheme = PartitionScheme(1, (rs,))
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", scheme)
    for s in servers:
        nc.register("ps", s.address, ttl_ms=500, tag_fn=s.claim_tag)
    reb = Rebalancer(reg_addr, "ps", VOCAB,
                     policy=RebalancePolicy(RebalanceOptions(
                         failback_sustain_s=0.0)))
    try:
        # failure-style promotion of replica 1
        ch = rpc.Channel(servers[1].address, timeout_ms=3000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch.close()
        assert servers[1].is_primary
        # replica 0 learns it was usurped on the next propagation —
        # poke it with a write so the Sync fences it
        ids = np.arange(8, dtype=np.int32)
        ch = rpc.Channel(servers[1].address, timeout_ms=3000)
        try:
            from brpc_tpu.ps_remote import _pack_apply_req
            ch.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                ids, np.full((8, DIM), 0.5, np.float32))))
        finally:
            ch.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and servers[0].is_primary:
            time.sleep(0.02)
        assert not servers[0].is_primary
        fb0 = int(obs.counter("ps_failbacks").get_value())
        # two steps: the first may only start the sustain window
        decided = None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and decided is None:
            decided = reb.step()
            time.sleep(0.05)
        assert decided is not None and decided.kind == "failback"
        assert int(obs.counter("ps_failbacks").get_value()) == fb0 + 1
        assert servers[0].epoch >= 2
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not servers[0].is_primary:
            time.sleep(0.02)
        assert servers[0].is_primary
    finally:
        reb.stop()
        nc.close()
        for s in servers:
            s.close()
        reg_server.close()


@pytest.mark.needs_native
def test_rebalancer_splits_on_sustained_load_end_to_end():
    """The full autonomous loop on real servers: sustained per-shard
    rate above the split threshold -> the rebalancer provisions the
    successor through its provisioner, drives the migration, retires
    the old scheme, and hands the old servers to on_retired — no
    operator call anywhere."""
    from brpc_tpu import rpc
    from brpc_tpu.naming import (NamingClient, PartitionScheme,
                                 ReplicaSet, publish_scheme)
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding
    reg_server, reg_addr = _registry(rpc)
    old = [PsShardServer(VOCAB, DIM, s, 2, lr=1.0, stream=True)
           for s in range(2)]
    sc1 = PartitionScheme(1, tuple(ReplicaSet.of(s.address)
                                   for s in old))
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", sc1)
    spawned = []
    retired = []

    def provisioner(version, num_shards):
        servers = [PsShardServer(VOCAB, DIM, s, num_shards, lr=1.0,
                                 stream=True, importing=True,
                                 scheme_version=version)
                   for s in range(num_shards)]
        spawned.extend(servers)
        return PartitionScheme(version, tuple(
            ReplicaSet.of(s.address) for s in servers))

    pol = RebalancePolicy(RebalanceOptions(
        split_qps=30.0, merge_qps=1.0, sustain_s=0.2,
        min_interval_s=0.5))
    reb = Rebalancer(reg_addr, "ps", VOCAB, policy=pol,
                     provisioner=provisioner,
                     on_retired=retired.append,
                     migrate_deadline_s=30.0, drain_deadline_s=8.0)
    emb = RemoteEmbedding.from_registry(reg_addr, "ps", VOCAB, DIM,
                                        timeout_ms=10000, watch=True)
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([s.table.copy() for s in old])
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                         np.float32))
        # sustained read load above the threshold while stepping
        decided = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and decided is None:
            for _ in range(10):
                emb.lookup(ids[:64])
            decided = reb.step()
        assert decided is not None and decided.kind == "split"
        assert decided.num_shards == 4
        # the split completed: the registry's active scheme is v2 and
        # the ledger is exact across it
        nodes, _ = nc.list("ps")
        from brpc_tpu.naming import parse_schemes
        schemes = parse_schemes(nodes)
        assert schemes[2].state == "active"
        assert schemes[1].state == "retired"
        assert retired and retired[0].version == 1
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([s.table for s in spawned]), expect)
        assert np.array_equal(emb.lookup(ids), expect)
    finally:
        reb.stop()
        emb.close()
        nc.close()
        for s in old + spawned:
            s.close()
        reg_server.close()


@pytest.mark.needs_native
def test_rebalancer_split_auto_hydrates_from_checkpoint_stores(tmp_path):
    """A policy-decided split on sources with attached checkpoint
    stores seeds every destination from the on-disk base BEFORE the
    copy phase: ps_rebalance_hydrations counts the seeded
    destinations and no source ships a wholesale range snapshot
    (ps_migrate_syncs_out stays flat)."""
    from brpc_tpu import rpc
    from brpc_tpu.durable import CheckpointStore
    from brpc_tpu.naming import (NamingClient, PartitionScheme,
                                 ReplicaSet, publish_scheme)
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding
    reg_server, reg_addr = _registry(rpc)
    old = [PsShardServer(VOCAB, DIM, s, 2, lr=1.0, stream=True)
           for s in range(2)]
    stores = {s: CheckpointStore(str(tmp_path / f"shard{s}"))
              for s in range(2)}
    for s, srv in enumerate(old):
        srv.attach_checkpoint(stores[s])   # arms the tee + first base
    sc1 = PartitionScheme(1, tuple(ReplicaSet.of(s.address)
                                   for s in old))
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", sc1)
    spawned = []

    def provisioner(version, num_shards):
        servers = [PsShardServer(VOCAB, DIM, s, num_shards, lr=1.0,
                                 stream=True, importing=True,
                                 scheme_version=version)
                   for s in range(num_shards)]
        spawned.extend(servers)
        return PartitionScheme(version, tuple(
            ReplicaSet.of(s.address) for s in servers))

    pol = RebalancePolicy(RebalanceOptions(
        split_qps=30.0, merge_qps=1.0, sustain_s=0.2,
        min_interval_s=0.5))
    reb = Rebalancer(reg_addr, "ps", VOCAB, policy=pol,
                     provisioner=provisioner,
                     migrate_deadline_s=30.0, drain_deadline_s=8.0,
                     checkpoint_stores=stores)
    emb = RemoteEmbedding.from_registry(reg_addr, "ps", VOCAB, DIM,
                                        timeout_ms=10000, watch=True)
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([s.table.copy() for s in old])
    hyd0 = int(obs.counter("ps_rebalance_hydrations").get_value())
    errs0 = int(obs.counter("ps_rebalance_hydrate_errors").get_value())
    syncs0 = int(obs.counter("ps_migrate_syncs_out").get_value())
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                         np.float32))
        decided = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and decided is None:
            for _ in range(10):
                emb.lookup(ids[:64])
            decided = reb.step()
        assert decided is not None and decided.kind == "split"
        assert decided.num_shards == 4
        # 2 sources x 2 overlapping destinations each, all seeded from
        # disk, none via a live wholesale range snapshot
        assert int(obs.counter(
            "ps_rebalance_hydrations").get_value()) == hyd0 + 4
        assert int(obs.counter(
            "ps_rebalance_hydrate_errors").get_value()) == errs0
        assert int(obs.counter(
            "ps_migrate_syncs_out").get_value()) == syncs0
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([s.table for s in spawned]), expect)
    finally:
        reb.stop()
        emb.close()
        nc.close()
        for s in old + spawned:
            s.close()
        for st in stores.values():
            st.close()
        reg_server.close()
