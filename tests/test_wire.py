"""Wire-contract tier: frame-schema registry, hardened parsers, and the
``wire-contract`` lint check.

Three layers under test:

1. the declarative schemas in :mod:`brpc_tpu.wire` are byte-identical
   to the hand-rolled hot-path packers they describe (the schema is the
   shared truth the lint and fuzzer both derive from);
2. the hardened parsers reject hostile counts/lengths with a clean
   :class:`wire.WireError` (EBADFRAME) — including the numpy
   ``count=-1`` whole-buffer re-interpretation that parsed SILENTLY
   before this tier;
3. the ``wire-contract`` lint check flags drifted/unpaired framings and
   unvalidated counts on seeded fixtures, and the SAME seeded asymmetry
   is caught at runtime by ``fuzz.parity_fuzz`` (static/dynamic parity,
   the lock-order discipline applied to framing).
"""

import os
import random
import struct
import tempfile
import textwrap

import numpy as np
import pytest

from brpc_tpu import naming, obs, resilience, wire
from brpc_tpu import ps_remote
from brpc_tpu.analysis import fuzz
from brpc_tpu.analysis.lint import run_lint


def _wire_findings(paths):
    return [f for f in run_lint(paths, checks=["wire-contract"])]


@pytest.fixture(autouse=True)
def _obs_on():
    """Counter-reading tests must pin obs themselves: earlier tier-1
    files (test_ps_native) deliberately leave obs disabled."""
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


# ---------------------------------------------------------------------------
# schema <-> hand-rolled parity
# ---------------------------------------------------------------------------

def test_windows_schema_matches_hand_rolled():
    rng = random.Random(7)
    for _ in range(25):
        d = {f"w{i}-{rng.randrange(999)}": rng.randrange(1 << 40)
             for i in range(rng.randrange(0, 5))}
        hand = ps_remote._pack_windows(d)
        ref = wire.REGISTRY["windows"].pack({
            "entries": [{"writer": w.encode(), "seq": q}
                        for w, q in d.items()]})
        assert hand == ref
        got, end = ps_remote._unpack_windows(hand)
        assert got == d and end == len(hand)
        vals, end2 = wire.REGISTRY["windows"].unpack(hand)
        assert end2 == len(hand)
        assert {e["writer"].decode(): e["seq"]
                for e in vals["entries"]} == d


def test_apply_schema_matches_hand_rolled():
    ids = np.array([3, 5, 5, 11], np.int32)
    grads = np.arange(16, dtype=np.float32).reshape(4, 4)
    hand = bytes(ps_remote._pack_apply_req(ids, grads))
    ref = wire.REGISTRY["apply_req"].pack({"ids": ids, "grads": grads},
                                          dim=4)
    assert hand == ref
    got_ids, got_grads = ps_remote._unpack_apply(hand, 0, 64, 4)
    np.testing.assert_array_equal(got_ids, ids)
    np.testing.assert_array_equal(got_grads, grads)


def test_apply_id_schema_matches_hand_rolled():
    ids = np.array([1, 2], np.int32)
    grads = np.ones((2, 4), np.float32)
    body = wire.REGISTRY["apply_req"].pack({"ids": ids, "grads": grads},
                                           dim=4)
    hand = bytes(ps_remote._pack_apply_id_req(
        "writer-a", 9, [("old-key", 4)], ids, grads))
    ref = wire.REGISTRY["apply_id_req"].pack({
        "writer": b"writer-a", "seq": 9,
        "guards": [{"key": b"old-key", "q": 4}], "body": body}, dim=4)
    assert hand == ref
    writer, seq, guards, got_body = ps_remote._unpack_apply_id(hand)
    assert (writer, seq, guards) == ("writer-a", 9, [("old-key", 4)])
    assert bytes(got_body) == body


def test_stream_frame_schema_matches_hand_rolled():
    hand = bytes(ps_remote._pack_stream_frame(1, 2, 3, b"payload"))
    ref = wire.REGISTRY["stream_frame"].pack(
        {"seq": 1, "epoch": 2, "gen": 3, "body": b"payload"})
    assert hand == ref


def test_every_schema_roundtrips_through_reference_impl():
    rng = random.Random(0)
    for name, sch in wire.REGISTRY.items():
        for _ in range(10):
            values = sch.example(rng, dim=4)
            payload = sch.pack(values, dim=4)
            _, end = sch.unpack(payload, dim=4)
            assert end == len(payload), name


# ---------------------------------------------------------------------------
# guard helpers + hardened parsers
# ---------------------------------------------------------------------------

def test_guard_helpers_raise_wire_error_with_code():
    with pytest.raises(wire.WireError):
        wire.need(b"abc", 0, 4)
    with pytest.raises(wire.WireError):
        wire.need(b"abc", -1, 1)
    with pytest.raises(wire.WireError):
        wire.check_count(-1, 100)
    with pytest.raises(wire.WireError):
        wire.check_count(101, 100)
    with pytest.raises(wire.WireError):
        wire.read("<q", b"abc")
    assert wire.check_count(5, 5) == 5
    try:
        wire.read("<q", b"")
    except wire.WireError as e:
        assert e.code == wire.EBADFRAME
        assert isinstance(e, ValueError)
    assert resilience.EBADFRAME == wire.EBADFRAME == 2013


def test_unpack_apply_rejects_negative_count():
    # count=-1 is numpy's "read everything": pre-hardening this parsed
    # SILENTLY, re-interpreting the whole payload as ids+grads
    p = struct.pack("<i", -1) + np.arange(16, dtype=np.int32).tobytes()
    with pytest.raises(wire.WireError):
        ps_remote._unpack_apply(p, 0, 1 << 30, 1)


def test_unpack_apply_rejects_oversized_count():
    p = struct.pack("<i", 2**31 - 1) + b"\0" * 64
    with pytest.raises(wire.WireError):
        ps_remote._unpack_apply(p, 0, 1 << 30, 4)


def test_unpack_windows_rejects_hostile_counts():
    with pytest.raises(wire.WireError):
        ps_remote._unpack_windows(struct.pack("<i", 2**31 - 1))
    with pytest.raises(wire.WireError):  # negative writer length
        ps_remote._unpack_windows(
            struct.pack("<i", 1) + struct.pack("<i", -8)
            + struct.pack("<q", 7))
    with pytest.raises(wire.WireError):  # truncated mid-entry
        ps_remote._unpack_windows(
            struct.pack("<i", 1) + struct.pack("<i", 3) + b"ab")


def test_unpack_apply_id_rejects_hostile_lengths():
    with pytest.raises(wire.WireError):
        ps_remote._unpack_apply_id(struct.pack("<i", -4) + b"\0" * 16)
    with pytest.raises(wire.WireError):
        ps_remote._unpack_apply_id(
            struct.pack("<i", 0) + struct.pack("<qi", 1, 2**31 - 1))


# ---------------------------------------------------------------------------
# naming-plane hardening
# ---------------------------------------------------------------------------

def test_parse_claims_survives_missing_or_non_string_addr():
    nodes = [{"tag": "3/8@e7P"},                       # no addr at all
             {"addr": 7, "tag": "2/8@e7P"},            # non-string addr
             {"addr": "127.0.0.1:1", "tag": "1/8@e7P"}]
    claims = naming.parse_claims(nodes)
    assert claims == {(None, 8, 1): (7, "127.0.0.1:1")}


def test_from_json_rejects_string_addresses():
    # tuple("abc") silently becomes ('a','b','c') — three garbage
    # one-char addresses — unless the shape is validated
    bad = '{"version": 1, "replica_sets": [{"addresses": "abc"}]}'
    with pytest.raises(ValueError):
        naming.PartitionScheme.from_json(bad)


def test_from_json_rejects_non_finite_weight():
    for w in ("1e999", "-1e999"):
        bad = ('{"version": 1, "weight": ' + w +
               ', "replica_sets": [{"addresses": ["h:1"]}]}')
        with pytest.raises(ValueError):
            naming.PartitionScheme.from_json(bad)


def test_parse_schemes_skips_hostile_records_without_raising():
    deep = naming.SCHEME_TAG_PREFIX + "[" * 4000 + "]" * 4000
    good = naming.PartitionScheme(
        version=2, replica_sets=(naming.ReplicaSet(("h:1",)),))
    nodes = [
        {"addr": "0.0.0.0:9", "tag": deep},
        {"addr": "0.0.0.0:9", "tag": 42},            # non-string tag
        {"addr": "0.0.0.0:9",
         "tag": naming.SCHEME_TAG_PREFIX + '{"version": "x"}'},
        {"addr": "0.0.0.0:2",
         "tag": naming.SCHEME_TAG_PREFIX + good.to_json()},
    ]
    out = naming.parse_schemes(nodes)
    assert list(out) == [2]


def test_shard_tag_parsers_reject_nonsense_numbers():
    assert naming.parse_shard_tag("-1/8") is None
    assert naming.parse_shard_tag("3/0") is None
    assert naming.parse_shard_tag("9/8") is None
    assert naming.parse_shard_tag("3/8") == (3, 8, 0)
    assert naming.parse_claim_tag("3/8@e-3P") is None
    assert naming.parse_claim_tag("3/8@v-2e3P") is None
    assert naming.parse_claim_tag("3/8@v2e3P") == (3, 8, 0, 3, True, 2)


@pytest.mark.needs_native
def test_set_schemes_strict_lenient_parity():
    """The strict path and the lenient ingest path must agree RECORD BY
    RECORD: ``strict=False`` skips exactly the records ``strict=True``
    raises on, counting each in ``ps_scheme_rejects``."""
    vocab = 256
    a = "127.0.0.1:7901"
    records = [
        naming.PartitionScheme(
            version=1, replica_sets=(naming.ReplicaSet((a,)),) * 4),
        naming.PartitionScheme(              # bounds end != vocab
            version=2, replica_sets=(naming.ReplicaSet((a,)),) * 2,
            bounds=(0, 64, 128)),
        naming.PartitionScheme(              # 5 shards don't divide 256
            version=3, replica_sets=(naming.ReplicaSet((a,)),) * 5),
        naming.PartitionScheme(
            version=4, replica_sets=(naming.ReplicaSet((a,)),) * 2,
            bounds=(0, 96, vocab)),
    ]
    strict_rejects = []
    for rec in records:
        emb = ps_remote.RemoteEmbedding([a], vocab, 4)
        try:
            emb.set_schemes([rec], strict=True)
        except ValueError:
            strict_rejects.append(rec.version)
        finally:
            emb.close()
    assert strict_rejects == [2, 3]
    emb = ps_remote.RemoteEmbedding([a], vocab, 4)
    try:
        before = obs.counter("ps_scheme_rejects").get_value()
        emb.set_schemes(records, strict=False)
        got = {v.version for v in emb.schemes()}
        assert got == {0, 1, 4}
        assert obs.counter("ps_scheme_rejects").get_value() - before \
            == len(strict_rejects)
    finally:
        emb.close()


# ---------------------------------------------------------------------------
# the wire-contract lint check on seeded fixtures
# ---------------------------------------------------------------------------

#: the seeded asymmetric pair (satellite fixture): the packer writes
#: (i32 a, i64 b) but the unpacker reads (i64 a, i64 b) — field-width
#: drift of exactly the kind docstring symmetry cannot catch
_DRIFT_FIXTURE = textwrap.dedent("""\
    import struct

    def _pack_rec(v):
        return struct.pack("<i", v["a"]) + struct.pack("<q", v["b"])

    def _unpack_rec(p):
        (a,) = struct.unpack_from("<q", p, 0)
        (b,) = struct.unpack_from("<q", p, 8)
        return a, b
""")


def _lint_tmp(source: str):
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "fixture.py")
        with open(path, "w") as f:
            f.write(source)
        return _wire_findings([d])


def test_lint_flags_seeded_pack_unpack_drift():
    findings = _lint_tmp(_DRIFT_FIXTURE)
    assert any("drift" in f.message and "'iq'" in f.message
               and "'qq'" in f.message for f in findings), \
        [f.message for f in findings]


def test_fuzzer_catches_the_same_seeded_drift_at_runtime():
    """Static/dynamic parity: the fixture the lint flags above must
    also fail ``parity_fuzz`` when executed."""
    ns = {}
    exec(_DRIFT_FIXTURE, ns)  # noqa: S102 — the fixture under test
    sch = wire.FrameSchema(
        name="rec", fields=(wire.Int("a", "<i"), wire.Int("b", "<q")))
    failures = fuzz.parity_fuzz(sch, ns["_pack_rec"],
                                ns["_unpack_rec"], seed=3, iters=20)
    assert failures, "runtime parity fuzz must catch the drifted pair"
    assert any(f.kind == "contract" for f in failures)
    # and a symmetric pair passes both ways
    def good_pack(v):
        return struct.pack("<i", v["a"]) + struct.pack("<q", v["b"])

    def good_unpack(p):
        return wire.read("<iq", p, 0, "rec")

    assert fuzz.parity_fuzz(sch, good_pack, good_unpack, seed=3,
                            iters=20) == []


def test_lint_flags_unpaired_framing_function():
    findings = _lint_tmp(textwrap.dedent("""\
        import struct

        def _pack_solo(a):
            return struct.pack("<q", a)
    """))
    assert any("unpaired framing" in f.message for f in findings)


def test_lint_flags_unvalidated_count_on_parse_path():
    findings = _lint_tmp(textwrap.dedent("""\
        import struct

        def _unpack_list(p):
            (count,) = struct.unpack_from("<i", p, 0)
            out = []
            for i in range(count):
                out.append(struct.unpack_from("<q", p, 4 + 8 * i))
            return out

        def _pack_list(vals):
            out = struct.pack("<i", len(vals))
            for v in vals:
                out += struct.pack("<q", v)
            return out
    """))
    assert any("bounds validation" in f.message and "'count'" in
               f.message for f in findings), \
        [f.message for f in findings]


def test_lint_accepts_guarded_symmetric_pair():
    findings = _lint_tmp(textwrap.dedent("""\
        import struct

        def check_count(n, limit):
            if not 0 <= n <= limit:
                raise ValueError(n)
            return n

        def _pack_list(vals):
            out = struct.pack("<i", len(vals))
            for v in vals:
                out += struct.pack("<q", v)
            return out

        def _unpack_list(p):
            (count,) = struct.unpack_from("<i", p, 0)
            check_count(count, (len(p) - 4) // 8)
            out = []
            for i in range(count):
                out.append(struct.unpack_from("<q", p, 4 + 8 * i))
            return out
    """))
    assert findings == [], [f.message for f in findings]


def test_lint_flags_native_endian_format():
    findings = _lint_tmp(textwrap.dedent("""\
        import struct

        def _pack_rec(a):
            return struct.pack("qq", a, a)

        def _unpack_rec(p):
            return struct.unpack_from("qq", p, 0)
    """))
    assert sum("little-endian" in f.message for f in findings) == 2


def test_fuzz_coverage_map_covers_every_declared_parser():
    covered = {c for cs in fuzz.coverage_map().values() for c in cs}
    for name in wire.REGISTRY:
        assert name in covered, f"schema {name} has no fuzz target"
    for qual in wire.TEXT_PARSERS:
        assert qual in covered, f"text parser {qual} has no fuzz target"


# ---------------------------------------------------------------------------
# ps_parse_rejects: malformed frames are visible in _status vars
# ---------------------------------------------------------------------------

@pytest.mark.needs_native
def test_malformed_unary_counts_ps_parse_rejects():
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import PsShardServer

    server = PsShardServer(64, 4, 0, 1)
    ch = rpc.Channel(server.address)
    try:
        before = obs.counter("ps_parse_rejects").get_value()
        before_m = obs.counter("ps_parse_rejects_ApplyGrad").get_value()
        bad = struct.pack("<i", -1) + b"\0" * 32
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "ApplyGrad", bad)
        assert ei.value.code == wire.EBADFRAME
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "Lookup", struct.pack("<i", 3) + b"\0" * 4)
        assert ei.value.code == wire.EBADFRAME
        assert obs.counter("ps_parse_rejects").get_value() \
            - before == 2
        assert obs.counter("ps_parse_rejects_ApplyGrad").get_value() \
            - before_m == 1
        # a well-formed call still serves
        ids = np.array([1, 2], np.int32)
        rsp = ch.call("Ps", "Lookup",
                      bytes(ps_remote._pack_lookup_req(ids)))
        assert len(rsp) == 2 * 4 * 4
    finally:
        ch.close()
        server.close()


# ---------------------------------------------------------------------------
# exact segmented matching for shared multi-frame handlers
# ---------------------------------------------------------------------------

def _lint_fake_package(tmp_path, source):
    """A fixture scanned AS the package: the dir is named ``brpc_tpu``
    so the registry-conformance arm (which gates on an in-package scan)
    runs against the fixture's ``ps_remote`` module."""
    pkg = tmp_path / "brpc_tpu"
    pkg.mkdir()
    (pkg / "ps_remote.py").write_text(textwrap.dedent(source))
    return _wire_findings([str(pkg)])


def test_registry_segment_declarations_are_consistent():
    segmented = [s for s in wire.REGISTRY.values() if s.segments]
    assert {s.name for s in segmented} >= {
        "sync_req", "promote_req", "scheme_fence_req",
        "migrate_sync_req", "gen_rsp", "epoch_gen_rsp",
        "writer_seq_rsp"}
    for sch in segmented:
        for site, keys in sch.segments:
            assert keys, f"{sch.name}: empty segment key set"
            assert site in sch.pack_sites + sch.unpack_sites, \
                f"{sch.name}: segment site {site} is not a declared " \
                f"pack/unpack site"


def test_segment_drift_flagged_where_subsequence_would_pass(tmp_path):
    """The upgrade's point: the Sync branch reads only (q, q) but a
    SIBLING branch supplies the third q, so the whole-function stream
    still contains 'qqq' as a subsequence — only exact matching keyed
    on the dispatch discriminant can see the drifted branch."""
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve_control(self, method, payload):
                if method == "Sync":
                    epoch, gen = struct.unpack_from("<qq", payload, 0)
                    return b""
                if method == "Tail":
                    (count,) = struct.unpack_from("<q", payload, 16)
                    return b""
                return b""
    """)
    seg = [f for f in findings
           if "segment 'Sync'" in f.message and "sync_req" in f.message]
    assert seg, [f.message for f in findings]
    assert "'qq'" in seg[0].message and "'qqq'" in seg[0].message
    assert "exact segmented match failed" in seg[0].message
    # and the old subsequence rule would NOT have fired here
    from brpc_tpu.analysis.lint import _is_subsequence
    assert _is_subsequence("qqq", "qq" + "q")


def test_stale_segment_declaration_flagged(tmp_path):
    # the handler exists but no branch dispatches on the declared key:
    # the segment declaration itself has rotted
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve_control(self, method, payload):
                if method == "Resync":
                    a, b, c = struct.unpack_from("<qqq", payload, 0)
                return b""
    """)
    stale = [f for f in findings
             if "no branch dispatching on 'Sync'" in f.message]
    assert stale, [f.message for f in findings]


def test_segment_exact_match_accepts_faithful_branch(tmp_path):
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve_control(self, method, payload):
                if method == "Sync":
                    epoch, gen, n = struct.unpack_from(
                        "<qqq", payload, 0)
                    return b""
                return b""
    """)
    assert not any("segment 'Sync'" in f.message for f in findings), \
        [f.message for f in findings]


# ---------------------------------------------------------------------------
# pre-branch header matching for shared multi-frame handlers
# ---------------------------------------------------------------------------

def test_registry_prebranch_declarations_are_consistent():
    withpre = [s for s in wire.REGISTRY.values() if s.prebranch]
    assert {s.name for s in withpre} >= {"lookup_req"}
    for sch in withpre:
        seg_sites = {site for site, _keys in sch.segments}
        for site, head in sch.prebranch:
            # a pre-branch stream anchors to a SEGMENTED site: the
            # whole point is splitting shared-header reads from the
            # per-branch remainder
            assert site in seg_sites, \
                f"{sch.name}: pre-branch site {site} has no segment " \
                f"declaration"
            assert isinstance(head, str), (sch.name, site)
    lk = wire.REGISTRY["lookup_req"]
    assert dict(lk.prebranch) == {
        "ps_remote.PsShardServer._serve": "i",
        "ps_remote.DevicePsShardServer._serve": "i"}


def test_prebranch_faithful_shared_header_accepted(tmp_path):
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve(self, method, payload):
                (count,) = struct.unpack_from("<i", payload, 0)
                if method == "Lookup":
                    return b""
                return b""
    """)
    # the registry-staleness arm flags every in-tree site the fixture
    # does not define — irrelevant here; the point is that the defined
    # _serve passes both the pre-branch and the segment arm
    bad = [f for f in findings
           if "pre-branch" in f.message or "segment 'Lookup'" in f.message]
    assert not bad, [f.message for f in findings]


def test_prebranch_read_moved_into_branch_is_stale(tmp_path):
    # the header read migrated inside the dispatch branch: the declared
    # pre-branch stream no longer matches what the shared prefix moves
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve(self, method, payload):
                if method == "Lookup":
                    (count,) = struct.unpack_from("<i", payload, 0)
                    return b""
                return b""
    """)
    stale = [f for f in findings
             if "pre-branch" in f.message and "stale" in f.message
             and "lookup_req" in f.message]
    assert stale, [f.message for f in findings]
    assert "'i'" in stale[0].message


def test_prebranch_doubled_header_read_flagged_exactly(tmp_path):
    """Subsequence matching would bless a doubled header read ('i' is a
    subsequence of 'ii'); the pre-branch stream is matched EXACTLY."""
    findings = _lint_fake_package(tmp_path, """\
        import struct

        class PsShardServer:
            def _serve(self, method, payload):
                (count,) = struct.unpack_from("<i", payload, 0)
                (flags,) = struct.unpack_from("<i", payload, 4)
                if method == "Lookup":
                    return b""
                return b""
    """)
    bad = [f for f in findings
           if "pre-branch" in f.message and "lookup_req" in f.message]
    assert bad, [f.message for f in findings]
    assert "'ii'" in bad[0].message
    from brpc_tpu.analysis.lint import _is_subsequence
    assert _is_subsequence("i", "ii")
