"""PS hot-path parallelism: native async fan-out (call_async/join),
read-parallel CPU shard serving (rwlock), and the device shard's
handle-generation scheme.  Pure-Python pieces (_bucket) run everywhere;
everything touching the native core is @needs_native; device-shard tests
additionally need a PJRT plugin (fake or real) and skip otherwise."""

import os
import struct

import numpy as np
import pytest

from brpc_tpu.ps_remote import (DevicePsShardServer, PsShardServer,
                                RemoteEmbedding)


# ---- _bucket (pure python) ----

@pytest.mark.parametrize("count,want", [
    (0, 1), (1, 1), (2, 2), (3, 4), (4, 4), (5, 8), (7, 8), (8, 8),
    (9, 16), (1023, 1024), (1024, 1024), (1025, 2048),
])
def test_bucket_rounds_up_to_power_of_two(count, want):
    assert DevicePsShardServer._bucket(count) == want


def test_bucket_is_monotonic_and_covers():
    prev = 0
    for count in range(0, 300):
        b = DevicePsShardServer._bucket(count)
        assert b >= max(count, 1)          # covers the batch
        assert b & (b - 1) == 0            # power of two
        assert b >= prev                   # monotonic in count
        prev = b


# ---- call_async vs call (native) ----

@pytest.mark.needs_native
def test_call_async_matches_sequential_byte_for_byte():
    from brpc_tpu import rpc

    srv = rpc.Server()
    srv.add_service("Echo", lambda method, req: method.encode() + req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    try:
        payloads = [b"", b"x", os.urandom(17), os.urandom(65536),
                    np.arange(4096, dtype=np.float32).tobytes()]
        sync = [ch.call("Echo", f"M{i}", p)
                for i, p in enumerate(payloads)]
        pending = [ch.call_async("Echo", f"M{i}", p)
                   for i, p in enumerate(payloads)]
        assert [c.join() for c in pending] == sync
    finally:
        ch.close()
        srv.close()


@pytest.mark.needs_native
def test_call_async_error_propagates_through_join():
    from brpc_tpu import rpc

    srv = rpc.Server()

    def handler(method, req):
        raise ValueError(f"boom on {method}")

    srv.add_service("Err", handler)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    try:
        call = ch.call_async("Err", "Kaboom", b"x")
        with pytest.raises(rpc.RpcError) as ei:
            call.join()
        assert "boom on Kaboom" in str(ei.value)
        # a joined (even failed) call is spent
        with pytest.raises(RuntimeError):
            call.join()
        # unknown-service failure also arrives at join, not at start
        bad = ch.call_async("Ghost", "Nope", b"")
        with pytest.raises(rpc.RpcError):
            bad.join()
    finally:
        ch.close()
        srv.close()


@pytest.mark.needs_native
def test_call_async_close_without_join_is_safe():
    from brpc_tpu import rpc

    srv = rpc.Server()
    srv.add_service("Echo", lambda method, req: req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    try:
        calls = [ch.call_async("Echo", "Echo", b"abandoned")
                 for _ in range(4)]
        for c in calls:
            c.close()   # waits for completion, frees — no leak, no crash
        for c in calls:
            c.close()   # idempotent
        assert ch.call("Echo", "Echo", b"still alive") == b"still alive"
    finally:
        ch.close()
        srv.close()


# ---- parallel fan-out client (native) ----

VOCAB, DIM, SHARDS = 64, 16, 4


@pytest.mark.needs_native
def test_parallel_lookup_matches_sequential_client():
    servers = [PsShardServer(VOCAB, DIM, i, SHARDS) for i in range(SHARDS)]
    addrs = [s.address for s in servers]
    par = RemoteEmbedding(addrs, VOCAB, DIM)
    seq = RemoteEmbedding(addrs, VOCAB, DIM, parallel=False)
    try:
        rng = np.random.default_rng(7)
        ids = rng.integers(0, VOCAB, size=(5, 6)).astype(np.int32)
        np.testing.assert_array_equal(par.lookup(ids), seq.lookup(ids))
        grads = rng.standard_normal((5, 6, DIM)).astype(np.float32)
        par.apply_gradients(ids, grads)   # all shards, concurrently
        np.testing.assert_array_equal(par.lookup(ids), seq.lookup(ids))
    finally:
        par.close()
        seq.close()
        for s in servers:
            s.close()


# ---- concurrent stress: no torn rows ----

def _row_deltas_are_whole(rows, init_rows):
    """Every served row must be a CONSISTENT snapshot: the delta from the
    initial table is a constant vector per row (apply-grads subtract a
    constant from the whole row, so a mixed delta within one row == a
    torn read)."""
    d = rows - init_rows
    return np.allclose(d.max(axis=-1), d.min(axis=-1), atol=1e-5)


def _hammer_one_shard(emb, init, vocab, rounds=25, lookups=8, applies=2):
    """call_async fan-out of concurrent Lookups racing ApplyGrads against
    ONE shard; returns False at the first torn row."""
    all_ids = np.arange(vocab, dtype=np.int32)
    grad = np.ones((vocab, emb.dim), np.float32)
    req_ids = struct.pack("<i", vocab) + all_ids.tobytes()
    req_grad = req_ids + grad.tobytes()
    ch = emb.channels[0]
    for _ in range(rounds):
        pending = [ch.call_async("Ps", "Lookup", req_ids)
                   for _ in range(lookups)]
        pending += [ch.call_async("Ps", "ApplyGrad", req_grad)
                    for _ in range(applies)]
        for i, call in enumerate(pending):
            rsp = call.join()
            if i < lookups:
                rows = np.frombuffer(rsp, np.float32).reshape(
                    vocab, emb.dim)
                if not _row_deltas_are_whole(rows, init):
                    return False
    return True


@pytest.mark.needs_native
def test_cpu_shard_no_torn_rows_under_read_write_race():
    vocab, dim = 64, 32
    server = PsShardServer(vocab, dim, 0, 1, lr=0.25)
    emb = RemoteEmbedding([server.address], vocab, dim, timeout_ms=30000)
    try:
        init = server.table.copy()
        assert _hammer_one_shard(emb, init, vocab)
        # and the write lock lost no update: 25 rounds x 2 applies of
        # all-ones grads at lr=0.25 move every element by exactly -12.5
        np.testing.assert_allclose(server.table, init - 12.5, atol=1e-4)
    finally:
        emb.close()
        server.close()


def _device_client():
    from brpc_tpu import rpc
    plugin = os.environ.get("BRT_PJRT_PLUGIN")
    if plugin is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for d in ("cpp/build", "build"):
            fake = os.path.join(root, d, "libbrt_fake_pjrt.so")
            if os.path.exists(fake):
                plugin = fake
                break
        else:
            pytest.skip("no PJRT plugin reachable (no fake built)")
    try:
        return rpc.DeviceClient(plugin)
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"no native PJRT device: {e}")


@pytest.mark.needs_native
def test_device_shard_no_torn_rows_and_racecheck_clean():
    """Lookups racing ApplyGrads on the HBM-resident shard: every served
    row is a whole generation (the handle-generation scheme makes torn
    rows impossible by construction), no update is lost, and RACECHECK
    no longer reports ps.device_shard held across blocking brt_device_*
    calls on the serving path."""
    from brpc_tpu.analysis import race

    vocab, dim = 16, 8
    dev = _device_client()
    race.clear()
    race.set_enabled(True)   # locks created by the server become checked
    try:
        server = DevicePsShardServer(vocab, dim, 0, 1, lr=1.0,
                                     device_client=dev)
        emb = RemoteEmbedding([server.address], vocab, dim,
                              timeout_ms=120000)
        try:
            init = server.table.copy()
            assert _hammer_one_shard(emb, init, vocab, rounds=10,
                                     lookups=4, applies=2)
            final = server.table
            assert _row_deltas_are_whole(final, init)
            # 10 rounds x 2 applies x lr=1.0 x grad=1: nothing lost
            np.testing.assert_allclose(final, init - 20.0, atol=1e-4)
        finally:
            emb.close()
            server.close()
        blocked = [f for f in race.findings()
                   if f.kind == "blocking-call"
                   and "ps.device_shard" in f.locks]
        assert blocked == [], race.report()
    finally:
        race.set_enabled(None)
        race.clear()
        dev.close()
