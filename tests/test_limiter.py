"""Overload control: adaptive concurrency limiting + deadline
propagation (brpc_tpu.limiter + the server/client wiring).

Three layers of proof:

1. the limiter state machines under a FAKE microsecond clock — window
   accounting, Little's-law limit setting, explore walk, all-failed
   halving, remeasure drain, shed-outcome exclusion — no wall time
   anywhere;
2. gate/ServerLimiter mechanics (method filtering, inflight
   accounting, shed counters);
3. live servers (native-gated): per-method ELIMIT shedding answers
   FAST while admitted work queues, the native Lookup path sheds via
   the new capi limiter, a deadline-expired request provably never
   mutates the table (exact arithmetic), EDEADLINE/ELIMIT are visible
   in counters and rpcz, retry treats ELIMIT as
   retriable-with-mandatory-backoff, and fault.py delay rules composed
   with the auto limiter drive the limit down and let it recover.
"""

import struct
import threading
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience, wire
from brpc_tpu.limiter import (AutoLimiter, AutoOptions, ConstantLimiter,
                              MethodGate, ServerLimiter, make_limiter)


# ---------------------------------------------------------------------------
# factory + constant
# ---------------------------------------------------------------------------

def test_make_limiter_specs():
    assert make_limiter(None) is None
    assert make_limiter("") is None
    assert make_limiter("none") is None
    assert make_limiter("off") is None
    assert make_limiter("constant") is None       # a constant needs one
    c = make_limiter("constant:7")
    assert isinstance(c, ConstantLimiter) and c.max_concurrency == 7
    assert isinstance(make_limiter("auto"), AutoLimiter)
    with pytest.raises(ValueError):
        make_limiter("gradient2")


def test_constant_limiter_admits_to_its_bound():
    c = ConstantLimiter(2)
    assert c.on_requested(1) and c.on_requested(2)
    assert not c.on_requested(3)
    assert ConstantLimiter(0).on_requested(10 ** 6)  # 0 = unlimited


# ---------------------------------------------------------------------------
# AutoLimiter under a fake clock
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, start: int = 0):
        self.now = start

    def __call__(self) -> int:
        return self.now


def _opts(**kw) -> AutoOptions:
    base = dict(initial_limit=40, min_limit=1, window_us=1000,
                min_samples=2, max_samples=1000, sample_interval_us=0,
                ema_alpha=0.5, remeasure_interval_us=10 ** 12)
    base.update(kw)
    return AutoOptions(**base)


def test_auto_window_sets_limit_by_littles_law():
    clk = FakeClock()
    lim = AutoLimiter(_opts(), clock_us=clk)
    assert lim.max_concurrency == 40
    # three successes at ~1ms latency spread over 1.2ms of clock: the
    # closing window estimates floor ~1001us, qps = 3 / 1.2ms = 2500/s
    # -> limit = floor*qps*(1+explore)+1 with explore at max 0.3
    for now in (100, 600, 1200):
        clk.now = now
        lim.on_responded(0, 1000)
    assert lim.max_concurrency != 40          # the window closed
    assert 2 <= lim.max_concurrency <= 6      # ~ 1001us * 2500/s * 1.3


def test_auto_all_failed_window_halves_the_limit():
    clk = FakeClock()
    lim = AutoLimiter(_opts(), clock_us=clk)
    for now in (100, 600, 1200):
        clk.now = now
        lim.on_responded(1008, 5000)
    assert lim.max_concurrency == 20          # 40 // 2


def test_auto_ignores_its_own_sheds():
    clk = FakeClock()
    lim = AutoLimiter(_opts(), clock_us=clk)
    for now in range(100, 5000, 100):
        clk.now = now
        lim.on_responded(2004, 1)             # ELIMIT: not a signal
        lim.on_responded(2014, 1)             # EDEADLINE: not a signal
    assert lim.max_concurrency == 40          # no window ever formed


def test_auto_small_window_is_discarded():
    clk = FakeClock()
    lim = AutoLimiter(_opts(min_samples=5), clock_us=clk)
    clk.now = 100
    lim.on_responded(0, 1000)
    clk.now = 2000                            # window expires with n=2
    lim.on_responded(0, 1000)
    assert lim.max_concurrency == 40


def test_auto_queueing_does_not_inflate_the_limit():
    clk = FakeClock()
    lim = AutoLimiter(_opts(), clock_us=clk)
    for now in (100, 600, 1200):              # healthy window: floor
        clk.now = now
        lim.on_responded(0, 1000)
    healthy = lim.max_concurrency
    # queueing: latency x20 at the same throughput — Vegas narrows the
    # explore ratio instead of chasing the inflated latency
    for now in (1300, 1800, 2400):
        clk.now = now
        lim.on_responded(0, 20000)
    assert lim.max_concurrency <= healthy + 1


def test_auto_remeasure_pulls_load_down_then_remeasures():
    clk = FakeClock()
    lim = AutoLimiter(_opts(remeasure_interval_us=2000), clock_us=clk)
    for now in (100, 600, 1200):
        clk.now = now
        lim.on_responded(0, 1000)
    # next window closes past the remeasure instant: the limiter pulls
    # the limit to reduce_ratio x estimate and enters the drain phase
    for now in (1300, 1900, 2600):
        clk.now = now
        lim.on_responded(0, 1000)
    drained = lim.max_concurrency
    # samples during the drain are ignored
    clk.now = 2700
    lim.on_responded(0, 999999)
    assert lim.max_concurrency == drained
    # after the drain expires, the floor re-measures from scratch
    clk.now = 3 * 10 ** 6
    lim.on_responded(0, 500)
    clk.now = 3 * 10 ** 6 + 600
    lim.on_responded(0, 500)
    clk.now = 3 * 10 ** 6 + 1300
    lim.on_responded(0, 500)
    assert lim.max_concurrency >= 1


# ---------------------------------------------------------------------------
# MethodGate / ServerLimiter mechanics
# ---------------------------------------------------------------------------

def test_method_gate_admits_and_sheds():
    g = MethodGate("Lookup", ConstantLimiter(2), "t")
    assert g.admit() and g.admit()
    assert g.inflight == 2
    assert not g.admit()                       # third refused
    assert g.inflight == 2 and g.shed == 1
    g.on_responded(0, 100)
    assert g.inflight == 1
    assert g.admit()                           # slot freed


def test_server_limiter_method_filter_and_lazy_gates():
    lim = ServerLimiter("constant:1", methods=("Lookup",),
                        counter_prefix="t")
    assert lim.gate("Promote") is None          # ungated control plane
    g = lim.gate("Lookup")
    assert g is not None and lim.gate("Lookup") is g
    assert g.admit() and not g.admit()
    assert lim.total_inflight() == 1
    assert lim.max_concurrency() == {"Lookup": 1}
    snap = lim.snapshot()
    assert snap["Lookup"]["shed"] == 1
    g.on_responded(0, 10)
    assert lim.total_inflight() == 0


def test_server_limiter_per_method_gates_are_independent():
    lim = ServerLimiter("constant:1", counter_prefix="t")
    a, b = lim.gate("Lookup"), lim.gate("ApplyGrad")
    assert a is not b
    assert a.admit() and b.admit()             # each has its own slot
    assert not a.admit()
    a.on_responded(0, 1)
    b.on_responded(0, 1)


def test_server_limiter_off_spec_gates_nothing():
    lim = ServerLimiter("none")
    assert lim.gate("Lookup") is None
    assert lim.total_inflight() == 0


def test_retry_policy_elimit_mandatory_backoff():
    pol = resilience.RetryPolicy(
        backoff=resilience.Backoff(base_ms=0.0, jitter=0.0),
        limit_backoff_floor_ms=7.0)
    err = resilience._rpc_error(resilience.ELIMIT, "shed")
    before = obs.counter("rpc_limit_backoffs").get_value()
    assert pol.retry_delay_ms(err, 0) == 7.0   # floored, never 0
    assert obs.counter("rpc_limit_backoffs").get_value() == before + 1
    other = resilience._rpc_error(1008, "timeout")
    assert pol.retry_delay_ms(other, 0) == 0.0  # only ELIMIT floors


# ---------------------------------------------------------------------------
# live servers (native)
# ---------------------------------------------------------------------------

@pytest.fixture
def shard_server():
    from brpc_tpu.ps_remote import PsShardServer
    servers = []

    def make(**kw):
        srv = PsShardServer(256, 8, 0, 1, **kw)
        servers.append(srv)
        return srv

    yield make
    for srv in servers:
        srv.close()


def _lookup_req(ids) -> bytes:
    a = np.asarray(ids, np.int32)
    return struct.pack("<i", a.size) + a.tobytes()


@pytest.mark.needs_native
def test_shed_answers_fast_while_admitted_work_queues(shard_server):
    """The shed-vs-queue latency bound: with a 250ms handler and a
    2-slot gate, refused requests answer ELIMIT in milliseconds while
    admitted ones take the full handler time."""
    from brpc_tpu import rpc
    srv = shard_server(limiter="constant:2")
    ch = rpc.Channel(srv.address, timeout_ms=5000)
    try:
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="Lookup", delay_ms=250)]))
        results = []

        def one():
            t0 = time.monotonic()
            try:
                ch.call("Ps", "Lookup", _lookup_req([1, 2]))
                results.append((0, time.monotonic() - t0))
            except rpc.RpcError as e:
                results.append((e.code, time.monotonic() - t0))

        ts = [threading.Thread(target=one) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        fault.clear()
        ch.close()
    codes = sorted(c for c, _ in results)
    assert codes.count(0) == 2
    assert codes.count(2004) == 6
    shed_lats = [lat for c, lat in results if c == 2004]
    ok_lats = [lat for c, lat in results if c == 0]
    assert max(shed_lats) < 0.15, shed_lats    # shed << queue
    assert min(ok_lats) >= 0.24                # admitted paid the work
    assert srv.limiter.snapshot()["Lookup"]["shed"] >= 6


@pytest.mark.needs_native
def test_native_lookup_path_sheds_via_capi_limiter(shard_server):
    """The zero-Python native Lookup path enforces the capi-installed
    limiter: concurrency beyond the bound answers ELIMIT from the C++
    dispatch, no Python anywhere."""
    from brpc_tpu import rpc
    srv = shard_server(native_read=True, limiter="constant:1")
    assert srv.server.native_max_concurrency == 1
    ch = rpc.Channel(srv.address, timeout_ms=5000)
    codes = []
    try:
        # the native limiter is server-wide: saturate the one slot
        # with a slow PYTHON method, then native Lookups must shed
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="ApplyGrad", delay_ms=300)]))
        grads = np.zeros((1, 8), np.float32)
        req = struct.pack("<i", 1) + np.array([1], np.int32).tobytes() \
            + grads.tobytes()

        def apply_slow():
            try:
                ch.call("Ps", "ApplyGrad", req)
                codes.append(0)
            except rpc.RpcError as e:
                codes.append(e.code)

        t = threading.Thread(target=apply_slow)
        t.start()
        time.sleep(0.08)                       # the slot is taken
        try:
            ch.call("Ps", "Lookup", _lookup_req([1, 2, 3]))
            codes.append(0)
        except rpc.RpcError as e:
            codes.append(e.code)
        t.join()
    finally:
        fault.clear()
        ch.close()
    assert 2004 in codes, codes


@pytest.mark.needs_native
def test_deadline_expired_request_never_mutates_table(shard_server):
    """The exact-arithmetic no-mutation proof: an expired ApplyGrad /
    ApplyGradId answers EDEADLINE before any table work, counted per
    method, and the table is byte-identical after."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import (_pack_apply_id_req, _pack_apply_req,
                                    _pack_deadline)
    srv = shard_server()
    ch = rpc.Channel(srv.address, timeout_ms=2000)
    try:
        before = srv.table.copy()
        ids = np.arange(4, dtype=np.int32)
        grads = np.full((4, 8), 0.25, np.float32)
        expired = int(time.time() * 1e6) - 1_000_000
        d0 = obs.counter("ps_deadline_drops").get_value()
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "ApplyGrad", bytes(_pack_deadline(
                expired, _pack_apply_req(ids, grads))))
        assert ei.value.code == resilience.EDEADLINE
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "ApplyGradId", bytes(_pack_deadline(
                expired, _pack_apply_id_req("w1", 1, (), ids, grads))))
        assert ei.value.code == resilience.EDEADLINE
        assert np.array_equal(before, srv.table)     # untouched, exactly
        assert obs.counter("ps_deadline_drops").get_value() == d0 + 2
        assert obs.counter(
            "ps_deadline_drops_ApplyGrad").get_value() >= 1
        assert obs.counter(
            "ps_deadline_drops_ApplyGradId").get_value() >= 1
        # a FUTURE deadline applies normally (the header peels away)
        rsp = ch.call("Ps", "ApplyGrad", bytes(_pack_deadline(
            int(time.time() * 1e6) + 5_000_000,
            _pack_apply_req(ids, grads))))
        del rsp
        after = before.copy()
        np.subtract.at(after, ids, srv.lr * grads)
        assert np.array_equal(after, srv.table)
        # shed spans carry the rpcz tag instead of vanishing
        spans = obs.dump_rpcz(limit=100, side="server",
                              errors_only=True)
        tags = [s["annotations"] for s in spans
                if s.get("error_code") == resilience.EDEADLINE]
        assert tags and all(t == ["shed=deadline"] for t in tags)
    finally:
        ch.close()


@pytest.mark.needs_native
def test_native_lookup_deadline_shed_and_peel(shard_server):
    """The NATIVE Lookup handler peels the deadline header: a future
    deadline serves (byte-identical to the bare framing), an expired
    one sheds with EDEADLINE — all with zero Python in the loop."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import _pack_deadline
    srv = shard_server(native_read=True)
    ch = rpc.Channel(srv.address, timeout_ms=2000)
    try:
        bare = _lookup_req([3, 4, 5])
        rsp = ch.call("Ps", "Lookup", bare)
        future = bytes(_pack_deadline(
            int(time.time() * 1e6) + 5_000_000, bare))
        assert ch.call("Ps", "Lookup", future) == rsp
        expired = bytes(_pack_deadline(
            int(time.time() * 1e6) - 1_000_000, bare))
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "Lookup", expired)
        assert ei.value.code == resilience.EDEADLINE
        assert srv.native_lookups >= 2         # both served natively
    finally:
        ch.close()


@pytest.mark.needs_native
def test_elimit_retries_with_mandatory_backoff_then_succeeds(
        shard_server):
    """The client contract: ELIMIT is retriable, but only after the
    mandatory backoff floor — a held slot releases during the backoff
    and the retry lands."""
    from brpc_tpu import rpc
    srv = shard_server(limiter="constant:1")
    ch = rpc.Channel(srv.address, timeout_ms=5000)
    try:
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="Lookup", delay_ms=150, max_hits=1)]))
        holder_done = []

        def holder():
            ch.call("Ps", "Lookup", _lookup_req([1]))
            holder_done.append(True)

        t = threading.Thread(target=holder)
        t.start()
        time.sleep(0.04)                       # the slot is held
        b0 = obs.counter("rpc_limit_backoffs").get_value()
        out = ch.call(
            "Ps", "Lookup", _lookup_req([2]),
            retry=resilience.RetryPolicy(
                max_attempts=8,
                backoff=resilience.Backoff(base_ms=0.0, jitter=0.0),
                limit_backoff_floor_ms=25.0))
        t.join()
        assert len(out) == 1 * 8 * 4
        assert holder_done
        assert obs.counter("rpc_limit_backoffs").get_value() > b0
    finally:
        fault.clear()
        ch.close()


@pytest.mark.needs_native
def test_fault_delay_composes_with_auto_limiter_drop_and_recover(
        shard_server):
    """Slow handler (fault delay rule) → the auto limiter's windows see
    inflated latency at low throughput and pull max_concurrency down
    from its warm-up ceiling; once the rule exhausts, served throughput
    and latency recover (the limit itself settles wherever Little's law
    puts it for the now-fast service — smaller is correct, not a
    failure to recover)."""
    from brpc_tpu import rpc
    opts = AutoOptions(initial_limit=12, min_limit=2,
                       window_us=60_000, min_samples=5,
                       max_samples=60, sample_interval_us=0)
    lim = ServerLimiter("auto", options=opts, methods=("Lookup",),
                        counter_prefix="ps")
    srv = shard_server()
    srv.limiter = lim
    srv.server.set_concurrency_limiter(lim)
    ch = rpc.Channel(srv.address, timeout_ms=5000)
    req = _lookup_req([1, 2, 3, 4])

    def hammer(seconds: float, oks: list, lats: list) -> None:
        stop = time.monotonic() + seconds
        while time.monotonic() < stop:
            t0 = time.monotonic()
            try:
                ch.call("Ps", "Lookup", req)
            except rpc.RpcError:
                resilience.sleep_ms(5)
                continue
            oks.append(1)
            lats.append(time.monotonic() - t0)

    def phase(seconds: float):
        oks: list = []
        lats: list = []
        ts = [threading.Thread(target=hammer,
                               args=(seconds, oks, lats))
              for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return len(oks), (sum(lats) / len(lats) if lats else 0.0)

    try:
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="Lookup", delay_ms=30)]))
        n_faulted, lat_faulted = phase(1.2)
        degraded = lim.gate("Lookup").max_concurrency
        assert degraded < 12                   # the limit came down
        assert lat_faulted >= 0.02             # the fault was real
        fault.clear()
        n_healthy, lat_healthy = phase(1.2)
        # recovery: the system SERVES again — more throughput at a
        # fraction of the latency, through the adapted limit
        assert n_healthy > 2 * n_faulted
        assert lat_healthy < lat_faulted / 3
        assert lim.gate("Lookup").max_concurrency >= opts.min_limit
    finally:
        fault.clear()
        ch.close()


@pytest.mark.needs_native
def test_remote_embedding_propagates_deadline_budget(shard_server):
    """RemoteEmbedding stamps its remaining budget: a server-side
    delay longer than the budget means the handler sees the request
    only after expiry — the server sheds it (counted) instead of
    mutating the table, and the table proves it."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import RemoteEmbedding
    srv = shard_server()
    emb = RemoteEmbedding([srv.address], 256, 8, deadline_ms=60,
                          retry=None)
    try:
        before = srv.table.copy()
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="ApplyGradId", delay_ms=150)]))
        d0 = obs.counter("ps_deadline_drops").get_value()
        with pytest.raises(rpc.RpcError):
            emb.apply_gradients(np.arange(4),
                                np.full((4, 8), 0.5, np.float32))
        # the server-side drop may land after the client's own timeout
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and \
                obs.counter("ps_deadline_drops").get_value() == d0:
            time.sleep(0.02)
        assert obs.counter("ps_deadline_drops").get_value() > d0
        assert np.array_equal(before, srv.table)
        # without the delay the same write applies fine
        fault.clear()
        emb.apply_gradients(np.arange(4),
                            np.full((4, 8), 0.5, np.float32))
        assert not np.array_equal(before, srv.table)
    finally:
        fault.clear()
        emb.close()


def test_deadline_header_roundtrip_and_magic_disambiguation():
    from brpc_tpu.ps_remote import _pack_deadline, _unpack_deadline
    body = b"\x07\x00\x00\x00payload"
    framed = bytes(_pack_deadline(123456789, body))
    out, dl = _unpack_deadline(framed)
    assert out == body and dl == 123456789
    # bare frames pass through untouched (no magic)
    out, dl = _unpack_deadline(body)
    assert out == body and dl == 0
    # magic present but truncated header: hostile, not legacy
    with pytest.raises(wire.WireError):
        _unpack_deadline(struct.pack("<i", wire.DEADLINE_MAGIC) + b"xx")
    # the magic cannot collide with a legitimate count field
    assert wire.DEADLINE_MAGIC > wire.MAX_WIRE_COUNT


def test_limiter_gauges_ride_status_vars():
    lim = ServerLimiter("constant:5", methods=("Lookup",),
                        counter_prefix="t")
    lim.gate("Lookup")
    obs.gauge("t_inflight", lim.total_inflight)
    obs.gauge("t_maxc",
              lambda: max(lim.max_concurrency().values(), default=0))
    try:
        d = obs.dump_exposed_dict("t_")
        assert d["t_inflight"] == 0 and d["t_maxc"] == 5
    finally:
        obs.drop_var("t_inflight")
        obs.drop_var("t_maxc")
        assert "t_inflight" not in obs.dump_exposed_dict("t_")


# ---------------------------------------------------------------------------
# deadline header v2 (relative budget + arrival stamp) and drain-time
# shedding (ISSUE 13 satellites)
# ---------------------------------------------------------------------------

def test_deadline_v2_pack_unpack_roundtrip():
    """The v2 header carries a RELATIVE budget; _unpack_deadline
    arrival-stamps it against the LOCAL clock — a positive budget
    yields a deadline just past now, a non-positive one a deadline in
    the past (shed at admission)."""
    from brpc_tpu.ps_remote import (_pack_deadline_rel,
                                    _unpack_deadline)
    body = b"\x01\x02\x03payload"
    framed = bytes(_pack_deadline_rel(250_000, body))
    assert struct.unpack_from("<i", framed, 0)[0] == \
        wire.DEADLINE_MAGIC2
    now_us = time.time() * 1e6
    out, deadline_us = _unpack_deadline(framed)
    assert out == body
    assert now_us + 100_000 < deadline_us < now_us + 1_000_000
    # expired budget: deadline lands at/behind the local arrival stamp
    out, deadline_us = _unpack_deadline(
        bytes(_pack_deadline_rel(-5, body)))
    assert out == body and deadline_us <= time.time() * 1e6
    # truncated v2 header is hostile, not legacy
    with pytest.raises(wire.WireError):
        _unpack_deadline(framed[:7])
    # bare frames still pass through untouched
    assert _unpack_deadline(body) == (body, 0)


@pytest.mark.needs_native
def test_deadline_v2_sheds_expired_work_server_side(shard_server):
    """A v2-stamped write whose budget is spent never mutates the
    table (EDEADLINE), on both the Python ApplyGrad path and the
    NATIVE Lookup parser; live budgets serve normally."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import (_pack_apply_req,
                                    _pack_deadline_rel)
    srv = shard_server(lr=1.0, native_read=True)
    ch = rpc.Channel(srv.address, timeout_ms=5000)
    ids = np.arange(8, dtype=np.int32)
    before = srv.table.copy()
    try:
        apply_body = bytes(_pack_apply_req(
            ids, np.full((8, 8), 0.5, np.float32)))
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "ApplyGrad",
                    bytes(_pack_deadline_rel(-1, apply_body)))
        assert ei.value.code == resilience.EDEADLINE
        assert np.array_equal(srv.table, before)
        # native Lookup peels the v2 magic: expired budget sheds with
        # EDEADLINE before the ids are even copied out
        native0 = srv.native_lookups
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("Ps", "Lookup",
                    bytes(_pack_deadline_rel(-1, _lookup_req(ids))))
        assert ei.value.code == resilience.EDEADLINE
        # a live budget serves through the same native path
        rsp = ch.call("Ps", "Lookup", bytes(_pack_deadline_rel(
            2_000_000, _lookup_req(ids))))
        assert len(rsp) == 8 * 8 * 4
        assert srv.native_lookups == native0 + 1
        # and the write path applies normally under a live v2 budget
        ch.call("Ps", "ApplyGrad", bytes(_pack_deadline_rel(
            2_000_000, apply_body)))
        expect = before.copy()
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(srv.table, expect)
    finally:
        ch.close()


@pytest.mark.needs_native
def test_remote_embedding_relative_deadline_mode(shard_server):
    """RemoteEmbedding(deadline_mode="relative") stamps every leg with
    the v2 header; a generous budget serves, an impossible one sheds
    at the server with EDEADLINE (never a silent apply)."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import RemoteEmbedding
    srv = shard_server(lr=1.0)
    ids = np.arange(8, dtype=np.int32)
    before = srv.table.copy()
    emb = RemoteEmbedding([srv.address], 256, 8, timeout_ms=5000,
                          deadline_ms=2000.0,
                          deadline_mode="relative")
    try:
        emb.apply_gradients(ids, np.full((8, 8), 0.5, np.float32))
        expect = before.copy()
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(srv.table, expect)
        # the stamp really is the v2 form: the header opens with the
        # v2 magic and carries (a tad under) the remaining budget
        framed = emb._stamp(b"body", time.monotonic() + 1.5)
        magic, budget_us = struct.unpack_from("<iq", framed, 0)
        assert magic == wire.DEADLINE_MAGIC2
        assert 1_000_000 < budget_us <= 1_500_000
        assert bytes(framed[12:]) == b"body"
    finally:
        emb.close()


def test_combiner_drain_time_deadline_shed():
    """The PR-12 deferral closed: a contribution whose deadline
    expires while WAITING in the combine queue is dropped at drain
    (counted, EDEADLINE to its waiter) — not applied."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import GradCombiner
    applied = []
    gate = threading.Event()

    def apply_fn(ids, grads):
        applied.append(np.array(ids))
        gate.wait(2.0)   # the leader's batch is slow: followers queue

    comb = GradCombiner(apply_fn, dim=4)
    drops0 = int(obs.counter("ps_deadline_drops_Drain").get_value())
    t_lead = threading.Thread(
        target=lambda: comb.add(np.array([1], np.int32),
                                np.zeros((1, 4), np.float32)))
    t_lead.start()
    time.sleep(0.05)     # the leader is inside apply_fn now
    # follower with a deadline that dies in the queue
    err = []

    def follower():
        try:
            comb.add(np.array([2], np.int32),
                     np.zeros((1, 4), np.float32),
                     deadline_us=int(time.time() * 1e6 + 50_000))
        except rpc.RpcError as e:
            err.append(e.code)

    t_f = threading.Thread(target=follower)
    t_f.start()
    time.sleep(0.2)      # its 50ms budget dies while queued
    gate.set()           # leader finishes; drain runs NOW
    t_lead.join(timeout=5)
    t_f.join(timeout=5)
    assert err == [resilience.EDEADLINE]
    # only the leader's contribution ever applied
    assert len(applied) == 1 and applied[0].tolist() == [1]
    assert int(obs.counter("ps_deadline_drops_Drain").get_value()) \
        == drops0 + 1
    # a LIVE follower behind the same slow leader still applies
    gate.clear()
    t_lead2 = threading.Thread(
        target=lambda: comb.add(np.array([3], np.int32),
                                np.zeros((1, 4), np.float32)))
    t_lead2.start()
    time.sleep(0.05)
    t_f2 = threading.Thread(
        target=lambda: comb.add(np.array([4], np.int32),
                                np.zeros((1, 4), np.float32),
                                deadline_us=int(time.time() * 1e6
                                                + 10_000_000)))
    t_f2.start()
    time.sleep(0.05)
    gate.set()
    t_lead2.join(timeout=5)
    t_f2.join(timeout=5)
    assert any(a.tolist() == [4] for a in applied)


@pytest.mark.needs_native
def test_combiner_drain_shed_through_server(shard_server):
    """End to end: a combined server whose leader batch is slowed by a
    fault delay sheds a queued v1-stamped write at drain — the table
    moves only by the surviving contributions (exact arithmetic)."""
    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import _pack_apply_req, _pack_deadline
    srv = shard_server(lr=1.0, combine=True)
    ch = rpc.Channel(srv.address, timeout_ms=8000)
    ids = np.arange(4, dtype=np.int32)
    body = bytes(_pack_apply_req(ids, np.full((4, 8), 0.5,
                                              np.float32)))
    before = srv.table.copy()
    # slow the COMBINER's apply itself (not the trampoline): the
    # follower must wait in the combine queue, where its budget dies
    orig = srv._combiner._apply
    in_apply = threading.Event()
    gate = threading.Event()

    def slow_apply(aids, agrads, metas=()):
        in_apply.set()
        gate.wait(5.0)
        orig(aids, agrads, metas)

    srv._combiner._apply = slow_apply
    try:
        t = threading.Thread(target=lambda: ch.call(
            "Ps", "ApplyGrad", body, timeout_ms=8000))
        t.start()
        assert in_apply.wait(5.0)    # the leader is mid-apply
        ch2 = rpc.Channel(srv.address, timeout_ms=8000)
        t2_err = []

        def follower():
            try:
                ch2.call("Ps", "ApplyGrad", bytes(_pack_deadline(
                    int(time.time() * 1e6 + 100_000), body)),
                    timeout_ms=8000)
            except rpc.RpcError as e:
                t2_err.append(e.code)

        t2 = threading.Thread(target=follower)
        t2.start()
        time.sleep(0.3)              # its 100ms budget dies queued
        gate.set()
        t.join(timeout=10)
        t2.join(timeout=10)
        assert t2_err == [resilience.EDEADLINE]
        expect = before.copy()
        expect[ids] -= np.float32(0.5)   # the leader alone applied
        assert np.array_equal(srv.table, expect)
        ch2.close()
    finally:
        srv._combiner._apply = orig
        fault.clear()
        ch.close()
