"""The lint gate: tier-1 fails if the framework-invariant linter finds
anything in brpc_tpu/ — new code must keep the ctypes contract complete,
handler state locked, instrumentation behind the obs helpers, and traced
functions pure."""

import os

import brpc_tpu
from brpc_tpu.analysis.lint import ALL_CHECKS, run_lint


def _pkg_dir() -> str:
    return os.path.dirname(os.path.abspath(brpc_tpu.__file__))


def test_package_lint_clean():
    findings = run_lint([_pkg_dir()])
    assert not findings, (
        "brpc_tpu/ must lint clean (python -m brpc_tpu.analysis):\n"
        + "\n".join(f.format() for f in findings))


def test_every_check_ran_against_real_surface():
    """The gate is only meaningful if the checks see their subject matter:
    the tree must actually contain brt_ declarations, handler classes,
    obs imports, and traced functions for the checks to chew on."""
    findings = run_lint([_pkg_dir()], checks=list(ALL_CHECKS))
    assert findings == []
    # a seeded violation in the same tree layout must flip the gate
    import tempfile
    import textwrap
    with tempfile.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad.py")
        with open(bad, "w") as f:
            f.write(textwrap.dedent("""\
                class H:
                    def __init__(self, srv):
                        srv.add_service("X", self._h)
                    def _h(self, m, r):
                        self.state = r
            """))
        assert run_lint([d]) != []
