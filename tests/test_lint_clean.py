"""The lint gate: tier-1 fails if the framework-invariant linter finds
anything NEW in brpc_tpu/ — new code must keep the ctypes contract
complete, handler-reachable state locked (across modules), traced call
chains pure, instrumentation behind the obs helpers, and checked-lock
nesting acyclic.

The gate diffs against ``tests/lint_baseline.json`` (stable finding
ids), the CI shape of ``python -m brpc_tpu.analysis --baseline``: an
accepted/deferred finding lands in the baseline instead of turning the
gate red for every later PR.  The baseline is currently empty — the
tree lints clean — so the gate is equivalent to strict mode until
something is deliberately deferred."""

import os

import brpc_tpu
from brpc_tpu.analysis.lint import (ALL_CHECKS, apply_baseline,
                                    load_baseline, run_lint)

_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "lint_baseline.json")


def _pkg_dir() -> str:
    return os.path.dirname(os.path.abspath(brpc_tpu.__file__))


def test_package_lint_clean_vs_baseline():
    baseline_ids = load_baseline(_BASELINE)
    new, suppressed = apply_baseline(run_lint([_pkg_dir()]), baseline_ids)
    assert not new, (
        "brpc_tpu/ must lint clean against tests/lint_baseline.json "
        "(python -m brpc_tpu.analysis --baseline tests/lint_baseline.json); "
        "new findings:\n" + "\n".join(f.format() for f in new))
    # the baseline must not rot: every accepted id still corresponds to
    # a live finding (stale ids mean the deferred item got fixed —
    # regenerate the baseline)
    live = {f.id for f in suppressed}
    stale = baseline_ids - live
    assert not stale, f"baseline ids no longer firing, regenerate: {stale}"


def test_every_check_ran_against_real_surface():
    """The gate is only meaningful if the checks see their subject matter:
    the tree must actually contain brt_ declarations, handler classes,
    obs imports, traced functions, and checked locks for the checks to
    chew on."""
    findings = run_lint([_pkg_dir()], checks=list(ALL_CHECKS))
    new, _ = apply_baseline(findings, load_baseline(_BASELINE))
    assert new == []
    # a seeded violation in the same tree layout must flip the gate
    import tempfile
    import textwrap
    with tempfile.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad.py")
        with open(bad, "w") as f:
            f.write(textwrap.dedent("""\
                class H:
                    def __init__(self, srv):
                        srv.add_service("X", self._h)
                    def _h(self, m, r):
                        self.state = r
            """))
        assert run_lint([d]) != []
