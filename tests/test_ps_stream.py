"""Write-path scale: server-side gradient combiner + native streaming
gradient push.

Covers the stream ABI surfaced as ``rpc.Stream`` /
``Server.add_stream_handler`` (ordered frames, backpressure stalls,
close-drains-in-flight, reject-without-accept), the
:class:`ps_remote.GradCombiner` (leader drains everything pending into
ONE application; error propagation; flush barrier), byte-level table
equivalence between unary / combined / streamed apply orderings
(commutative exact-arithmetic sums), torn-row/no-lost-update stress for
combined writes racing NATIVE reads (RACECHECK clean), and stream
reconnect driven by a server-side ``drop`` fault rule (the client's REAL
timeout path, closing the PR-5 deferral)."""

import struct
import threading
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience, rpc
from brpc_tpu.ps_remote import (GradCombiner, PsShardServer,
                                RemoteEmbedding, _pack_apply_req)

pytestmark = pytest.mark.needs_native

VOCAB, DIM = 256, 8


@pytest.fixture(autouse=True)
def _obs_on():
    # earlier suites may leave obs disabled (test_ps_native's counter
    # tests switch it off on exit); these tests read counters
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _apply_frame_bytes(ids, grads):
    return bytes(_pack_apply_req(np.asarray(ids, np.int32),
                                 np.asarray(grads, np.float32)))


# ---- stream ABI: rpc.Stream / Server.add_stream_handler ----

class _Collector:
    def __init__(self):
        self.frames = []
        self.closed = threading.Event()

    def on_data(self, data):
        self.frames.append(data)

    def on_closed(self):
        self.closed.set()


def test_stream_roundtrip_ordered_close_drains():
    got = _Collector()

    def handler(method, request, accept):
        assert method == "Open"
        accept(got)
        return b"hello:" + request

    srv = rpc.Server()
    srv.add_stream_handler("S", handler)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=10000)
    try:
        st = ch.stream("S", "Open", b"cfg")
        assert st.response == b"hello:cfg"
        frames = [bytes([i % 251]) * (1 + i * 7) for i in range(64)]
        for f in frames:
            st.write(f)
        st.close()
        # close is graceful: every in-flight frame drains IN ORDER
        # before on_closed; join returns only after the peer closed too
        assert st.join(timeout_s=10)
        assert got.closed.wait(5)
        assert got.frames == frames
        # idempotent close / writes after close fail cleanly
        st.close()
        with pytest.raises(rpc.RpcError):
            st.write(b"late")
    finally:
        ch.close()
        srv.close()


class _Echoer:
    """Server receiver that writes every frame back on the server half
    (the write surface ``accept`` now returns)."""

    def __init__(self):
        self.reply = None
        self.closed = threading.Event()

    def on_data(self, data):
        self.reply.write(b"echo:" + data)

    def on_closed(self):
        self.closed.set()


def test_server_to_client_stream_writes():
    """The PR-7 deferral closed: the native stream layer is symmetric,
    and ``Channel.stream(receiver=...)`` surfaces the read side — the
    server's handler gets a writable server half back from ``accept``
    and frames it writes deliver to the client's receiver, serialized,
    with a final ``on_closed`` after the server closes its half."""
    server_side = _Echoer()

    def handler(method, request, accept):
        server_side.reply = accept(server_side)
        return b"ok"

    srv = rpc.Server()
    srv.add_stream_handler("S", handler)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=10000)
    client_side = _Collector()
    try:
        st = ch.stream("S", "Open", b"", receiver=client_side)
        assert st.response == b"ok"
        frames = [f"f{i}".encode() for i in range(32)]
        for f in frames:
            st.write(f)
        # collect every echo BEFORE closing: close is a full close, not
        # a half-close — peer frames after it are discarded by design
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                len(client_side.frames) < len(frames):
            time.sleep(0.005)
        assert client_side.frames == [b"echo:" + f for f in frames]
        st.close()
        assert server_side.closed.wait(5)
        server_side.reply.close()
        assert client_side.closed.wait(5)
    finally:
        ch.close()
        srv.close()


def test_rx_stream_delivers_frames_written_before_registration():
    """A fast server can write frames that arrive BEFORE the client's
    receiver registration lands: they buffer as orphans and the
    registration drains them in order (the two-phase handoff)."""
    class _Greeter:
        def on_data(self, data):
            pass

        def on_closed(self):
            pass

    holder = {}

    def handler(method, request, accept):
        reply = accept(_Greeter())
        # written INSIDE the handler — the client cannot have
        # registered yet (the setup response hasn't even left)
        reply.write(b"early-1")
        reply.write(b"early-2")
        holder["reply"] = reply
        return b"ok"

    srv = rpc.Server()
    srv.add_stream_handler("S", handler)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=10000)
    got = _Collector()
    try:
        st = ch.stream("S", "Open", b"", receiver=got)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(got.frames) < 2:
            time.sleep(0.005)
        assert got.frames == [b"early-1", b"early-2"]
        st.close()
        holder["reply"].close()
        assert got.closed.wait(5)
    finally:
        ch.close()
        srv.close()


def test_stream_rejected_when_handler_does_not_accept():
    srv = rpc.Server()
    srv.add_stream_handler("S", lambda m, r, accept: b"no-stream")
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    try:
        with pytest.raises(rpc.RpcError) as ei:
            ch.stream("S", "Open")
        assert ei.value.code == 1003  # EREQUEST: peer never accepted
        # plain unary methods on the same service keep working
        assert ch.call("S", "Anything") == b"no-stream"
    finally:
        ch.close()
        srv.close()


def test_backpressure_stalls_writer_and_feeds_counter():
    """A slow receiver behind a small window parks the writer: writes
    take real wall time and the stalled time lands in stream_stall_ms."""
    before = obs.counter("stream_stall_ms").get_value()
    got = _Collector()
    slow = _Collector()
    slow.on_data = lambda data, _g=got: (time.sleep(0.015),
                                         _g.frames.append(data))

    srv = rpc.Server()
    srv.add_stream_handler(
        "S", lambda m, r, accept: (accept(slow, max_buf_size=8192), b"")[1])
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=10000)
    try:
        st = ch.stream("S", "Open", max_buf_size=8192)
        t0 = time.monotonic()
        for _ in range(24):
            st.write(b"x" * 4096)
        wall = time.monotonic() - t0
        st.close()
        assert st.join(timeout_s=10)
        assert len(got.frames) == 24
        # 24 * 4KB through an 8KB window at ~15ms/frame: the writer MUST
        # have parked waiting for consumed-bytes credit
        assert wall > 0.15
        assert obs.counter("stream_stall_ms").get_value() - before > 50
    finally:
        ch.close()
        srv.close()


# ---- GradCombiner unit semantics ----

def test_combiner_leader_drains_pending_into_one_apply():
    """While the leader's apply is in flight, everything that queues up
    combines into the NEXT single application (one apply for N adds)."""
    applied = []
    release = threading.Event()
    first_started = threading.Event()

    def apply_fn(ids, grads):
        if not applied:
            first_started.set()
            release.wait(5)
        applied.append((ids.copy(), grads.copy()))

    c = GradCombiner(apply_fn, DIM)
    g = np.ones((1, DIM), np.float32)
    leader = threading.Thread(
        target=c.add, args=(np.array([0], np.int32), g))
    leader.start()
    assert first_started.wait(5)
    followers = [threading.Thread(
        target=c.add, args=(np.array([i], np.int32), i * g))
        for i in (1, 2, 3)]
    for t in followers:
        t.start()
    # followers are queued behind the in-flight apply, not applying
    time.sleep(0.05)
    assert len(applied) == 1 or not applied
    release.set()
    leader.join(5)
    for t in followers:
        t.join(5)
    assert len(applied) == 2  # leader's own + ONE combined batch of 3
    batch_ids = sorted(applied[1][0].tolist())
    assert batch_ids == [1, 2, 3]
    assert obs.maxer("ps_combine_depth").get_value() >= 3


def test_combiner_error_propagates_to_every_waiter_then_recovers():
    calls = []

    def apply_fn(ids, grads):
        calls.append(ids.size)
        if len(calls) == 1:
            raise ValueError("boom")

    c = GradCombiner(apply_fn, DIM)
    with pytest.raises(ValueError, match="boom"):
        c.add(np.array([1], np.int32), np.ones((1, DIM), np.float32))
    assert isinstance(c.last_error, ValueError)
    # the combiner is not wedged: the next batch applies
    c.add(np.array([2], np.int32), np.ones((1, DIM), np.float32))
    assert len(calls) == 2


def test_combiner_flush_is_an_applied_barrier():
    applied = []
    c = GradCombiner(lambda i, g: applied.append(i.size), DIM)
    c.add(np.array([1, 2], np.int32), np.ones((2, DIM), np.float32),
          wait=False)
    c.flush()
    assert applied == [2]


# ---- byte-level equivalence: unary == combined == streamed ----

def _integer_table(server, rng):
    """Overwrite the shard's table with exactly-representable values
    (multiples of 0.5): with integer grads and lr=0.5 every intermediate
    value is exact in float32, so application ORDER cannot change a
    single bit — the commutative-sum property the equivalence test
    needs."""
    t = rng.integers(-50, 50, server.table.shape).astype(np.float32) * 0.5
    server.table[:] = t
    return t.copy()


def _hammer(address, chunks, mode):
    """8 concurrent writers, one chunk each, via `mode`."""
    def work(chunk):
        emb = RemoteEmbedding([address], VOCAB, DIM, timeout_ms=30000)
        try:
            if mode == "stream":
                emb.push_gradients(chunk[0], chunk[1])
                emb.flush_gradients()
            else:
                emb.apply_gradients(chunk[0], chunk[1])
        finally:
            emb.close()
    threads = [threading.Thread(target=work, args=(c,)) for c in chunks]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)


def test_unary_combined_stream_byte_equivalence():
    """The acceptance-criteria proof: the SAME multiset of exact
    gradient contributions applied through the unary path, the combiner,
    and the stream (8 concurrent writers each, arbitrary interleavings)
    lands the byte-identical table — combining is a pure reordering of a
    commutative sum."""
    rng = np.random.default_rng(11)
    ids = rng.integers(0, VOCAB, 512).astype(np.int32)
    grads = rng.integers(-4, 5, (512, DIM)).astype(np.float32)
    chunks = [(ids[i::8], grads[i::8]) for i in range(8)]
    tables = {}
    for mode, kw in (
            ("unary", {}),
            ("combined", dict(combine=True)),
            ("stream", dict(combine=True, stream=True))):
        s = PsShardServer(VOCAB, DIM, 0, 1, lr=0.5, seed=3,
                          native_read=True, **kw)
        try:
            base = _integer_table(s, np.random.default_rng(5))
            _hammer(s.address, chunks, mode)
            tables[mode] = s.table.copy()
        finally:
            s.close()
    expect = base
    np.subtract.at(expect, ids, 0.5 * grads)
    for mode, got in tables.items():
        assert np.array_equal(got, expect), f"{mode} lost/None updates"
    assert np.array_equal(tables["unary"], tables["combined"])
    assert np.array_equal(tables["unary"], tables["stream"])


# ---- torn-row / no-lost-update stress vs native reads (RACECHECK) ----

def test_combined_writes_race_native_reads_racecheck_clean():
    """Streamed + unary combined writes racing the NATIVE read path:
    every row a reader sees is a whole generation snapshot (no torn
    rows), no update is lost, and RACECHECK reports no lock held across
    a blocking call on either path."""
    from brpc_tpu.analysis import race

    vocab, dim = 64, 16
    race.clear()
    race.set_enabled(True)
    try:
        s = PsShardServer(vocab, dim, 0, 1, lr=0.25, native_read=True,
                          combine=True, stream=True)
        ch = rpc.Channel(s.address, timeout_ms=30000)
        try:
            init = s.table.copy()
            all_ids = np.arange(vocab, dtype=np.int32)
            req_ids = bytes(struct.pack("<i", vocab) + all_ids.tobytes())
            grad = np.ones((vocab, dim), np.float32)
            frame = _apply_frame_bytes(all_ids, grad)

            stop = threading.Event()
            torn = []

            def reader():
                rch = rpc.Channel(s.address, timeout_ms=30000)
                try:
                    while not stop.is_set():
                        rows = np.frombuffer(
                            rch.call("Ps", "Lookup", req_ids),
                            np.float32).reshape(vocab, dim)
                        d = rows - init
                        if not np.allclose(d.max(axis=-1), d.min(axis=-1),
                                           atol=1e-5):
                            torn.append(d)
                            return
                finally:
                    rch.close()

            readers = [threading.Thread(target=reader) for _ in range(2)]
            for t in readers:
                t.start()
            # 4 unary writers + 2 stream pushers, whole-row deltas
            rounds = 10
            def unary_writer():
                wch = rpc.Channel(s.address, timeout_ms=30000)
                try:
                    for _ in range(rounds):
                        wch.call("Ps", "ApplyGrad", frame)
                finally:
                    wch.close()

            def stream_writer():
                wch = rpc.Channel(s.address, timeout_ms=30000)
                try:
                    st = wch.stream("Ps", "StreamApply")
                    for _ in range(rounds):
                        st.write(frame)
                    st.close()
                    assert st.join(timeout_s=30)
                finally:
                    wch.close()

            writers = [threading.Thread(target=unary_writer)
                       for _ in range(4)]
            writers += [threading.Thread(target=stream_writer)
                        for _ in range(2)]
            for t in writers:
                t.start()
            for t in writers:
                t.join(60)
            stop.set()
            for t in readers:
                t.join(30)
            assert not torn, "reader saw a torn row"
            # 6 writers x 10 rounds x lr 0.25 x all-ones = exactly -15.0
            np.testing.assert_allclose(s.table, init - 15.0, atol=1e-4)
            assert s.native_lookups > 0
        finally:
            ch.close()
            s.close()
        blocked = [f for f in race.findings()
                   if f.kind == "blocking-call"
                   and ("ps.shard" in f.locks or "ps.combine" in f.locks)]
        assert blocked == [], race.report()
    finally:
        race.set_enabled(None)
        race.clear()


# ---- push_gradients / flush barrier ----

def test_push_gradients_flush_barrier_and_reuse():
    s = PsShardServer(VOCAB, DIM, 0, 1, lr=0.5, stream=True)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=20000)
    try:
        base = s.table.copy()
        ids = np.arange(16, dtype=np.int32)
        g = np.ones((16, DIM), np.float32)
        emb.push_gradients(ids, g)
        emb.flush_gradients()
        np.testing.assert_allclose(s.table[:16], base[:16] - 0.5,
                                   atol=1e-6)
        # streams reopen lazily: a second push round works
        emb.push_gradients(ids, g)
        emb.flush_gradients()
        np.testing.assert_allclose(s.table[:16], base[:16] - 1.0,
                                   atol=1e-6)
        assert obs.counter("ps_combined_applies").get_value() > 0
    finally:
        emb.close()
        s.close()


def test_stream_frame_error_is_counted_not_fatal():
    """An out-of-range streamed delta cannot answer an error (frames
    have no response): it is counted and the shard stays healthy."""
    before = obs.counter("stream_handler_errors").get_value()
    s = PsShardServer(VOCAB, DIM, 0, 2, stream=True)  # owns rows [0,128)
    ch = rpc.Channel(s.address, timeout_ms=10000)
    try:
        st = ch.stream("Ps", "StreamApply")
        bad = _apply_frame_bytes(np.array([200], np.int32),
                                 np.ones((1, DIM), np.float32))
        st.write(bad)
        st.close()
        assert st.join(timeout_s=10)
        assert obs.counter("stream_handler_errors").get_value() > before
        # the unary path still serves
        req = struct.pack("<i", 1) + np.array([5], np.int32).tobytes()
        assert len(ch.call("Ps", "Lookup", bytes(req))) == DIM * 4
    finally:
        ch.close()
        s.close()


# ---- stream reconnect via a SERVER-side drop rule (PR-5 deferral) ----

def test_server_drop_rule_exercises_real_timeout_path():
    """A server-side drop rule discards the request pre-dispatch: the
    handler never runs, no response is written, and the client's REAL
    deadline expires (ERPCTIMEDOUT after ~timeout, not an instant
    error)."""
    ran = []
    srv = rpc.Server()
    srv.add_service("E", lambda m, d: ran.append(m) or b"pong")
    port = srv.start("127.0.0.1:0")
    plan = fault.FaultPlan([fault.FaultRule(
        action="drop", side="server", service="E", max_hits=1)])
    fault.install(plan)
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=300, max_retry=0)
    try:
        before = obs.counter("fault_injected_drops").get_value()
        t0 = time.monotonic()
        with pytest.raises(rpc.RpcError) as ei:
            ch.call("E", "Ping")
        assert ei.value.code == 1008  # the client's own deadline fired
        assert time.monotonic() - t0 > 0.25
        assert ran == []  # never dispatched
        assert obs.counter("fault_injected_drops").get_value() == before + 1
        assert ch.call("E", "Ping") == b"pong"  # max_hits exhausted
    finally:
        fault.clear()
        ch.close()
        srv.close()


def test_push_reconnects_through_dropped_stream_setup():
    """The drop rule hits the StreamApply SETUP call: stream creation
    times out for real, and push_gradients reconnects under the retry
    budget — closing the loop the PR-5 deferral asked for."""
    s = PsShardServer(VOCAB, DIM, 0, 1, lr=0.5, stream=True)
    plan = fault.FaultPlan([fault.FaultRule(
        action="drop", side="server", service="Ps", method="StreamApply",
        max_hits=1)])
    fault.install(plan)
    emb = RemoteEmbedding(
        [s.address], VOCAB, DIM, timeout_ms=400,
        retry=resilience.RetryPolicy(
            max_attempts=3,
            backoff=resilience.Backoff(base_ms=5.0, max_ms=20.0)))
    try:
        base = s.table.copy()
        before = obs.counter("ps_stream_reconnects").get_value()
        ids = np.arange(8, dtype=np.int32)
        emb.push_gradients(ids, np.ones((8, DIM), np.float32))
        emb.flush_gradients()
        np.testing.assert_allclose(s.table[:8], base[:8] - 0.5, atol=1e-6)
        assert obs.counter("ps_stream_reconnects").get_value() == \
            before + 1
        assert plan.hits() == [1]
    finally:
        fault.clear()
        emb.close()
        s.close()
