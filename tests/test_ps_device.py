"""Device-resident PS shard: the embedding table lives in HBM behind a
native buffer handle; Lookup/ApplyGrad are compiled gather/scatter-sub
launches and bytes ride the native staging fabric (no JAX in the serving
path). Skips when no PJRT plugin/device is reachable."""

import struct
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience, rpc
from brpc_tpu.durable import CheckpointStore
from brpc_tpu.naming import (NamingClient, PartitionScheme, ReplicaSet,
                             publish_scheme)
from brpc_tpu.ps_remote import (DevicePsShardServer, RemoteEmbedding,
                                _pack_apply_req)
from brpc_tpu.rebalance import (RebalanceOptions, RebalancePolicy,
                                Rebalancer)
from brpc_tpu.reshard import MigrationDriver

VOCAB, DIM = 16, 8


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)
    fault.clear()


import functools


@functools.lru_cache(maxsize=1)
def _axon_tunnel_alive() -> bool:
    # The axon plugin talks to a local relay; the relay's port being open is
    # NOT enough (a wedged tunnel accepts connects but blocks client init
    # forever), so probe by actually creating a device client in a child
    # process under a hard deadline. Cached: the tunnel state won't flip
    # mid-run, and the probe costs seconds.
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.settimeout(0.5)
    try:
        s.connect(("127.0.0.1", 8082))
    except OSError:
        return False
    finally:
        s.close()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from brpc_tpu import rpc; rpc.DeviceClient().close(); "
             "print('ok')"],
            capture_output=True, text=True, timeout=60, cwd=repo_root)
    except (subprocess.TimeoutExpired, OSError):
        return False
    return proc.returncode == 0 and "ok" in proc.stdout


def _device_client():
    import os
    plugin = os.environ.get("BRT_PJRT_PLUGIN")
    if plugin is None and not _axon_tunnel_alive():
        # Deterministic fallback: the in-repo fake N-device plugin (same
        # one the native multi-replica tests use).
        fake = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "cpp", "build",
            "libbrt_fake_pjrt.so")
        if not os.path.exists(fake):
            fake = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "build", "libbrt_fake_pjrt.so")
        if os.path.exists(fake):
            plugin = fake
        else:
            pytest.skip("no PJRT plugin reachable (tunnel down, no fake)")
    try:
        return rpc.DeviceClient(plugin)
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"no native PJRT device: {e}")


@pytest.fixture(scope="module")
def shard():
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    yield s, emb
    emb.close()
    s.close()
    dev.close()


# allow_handle_leak: the module-scoped `shard` fixture compiles its
# gather/scatter executables lazily inside these tests and caches them
# for the module's lifetime — net-per-test handle growth is the cache
# filling, released at fixture teardown, not a leak.
@pytest.mark.allow_handle_leak
def test_device_lookup_matches_resident_table(shard):
    s, emb = shard
    host = s.table  # DMA snapshot of the HBM-resident table
    ids = np.array([0, 3, 7, 15], np.int32)
    rows = emb.lookup(ids)
    np.testing.assert_allclose(rows, host[ids], rtol=1e-6)


@pytest.mark.allow_handle_leak  # same module-fixture exe-cache growth
def test_device_apply_grad_updates_hbm_table(shard):
    s, emb = shard
    before = s.table
    ids = np.array([1, 2, 5, 5], np.int32)  # duplicate: must accumulate
    grads = np.ones((4, DIM), np.float32)
    emb.apply_gradients(ids, grads)
    after = s.table
    np.testing.assert_allclose(after[1], before[1] - 0.5, rtol=1e-5)
    np.testing.assert_allclose(after[2], before[2] - 0.5, rtol=1e-5)
    # row 5 got BOTH contributions (scatter-add semantics on device)
    np.testing.assert_allclose(after[5], before[5] - 1.0, rtol=1e-5)
    # untouched rows stay put
    np.testing.assert_allclose(after[0], before[0], rtol=1e-6)


def test_device_training_step_roundtrip(shard):
    s, emb = shard
    ids = np.array([4, 6, 8, 9], np.int32)
    target = np.zeros((4, DIM), np.float32)
    first_loss = None
    for _ in range(5):
        rows = emb.lookup(ids)
        loss = float(((rows - target) ** 2).mean())
        if first_loss is None:
            first_loss = loss
        emb.apply_gradients(ids, rows - target)
    assert float(((emb.lookup(ids) - target) ** 2).mean()) < first_loss


def test_device_combiner_single_launch_no_wasted_scatters():
    """combine=True routes every ApplyGrad through the combiner: the
    lost-swap redo loop never races itself (one installer at a time), so
    wasted scatter launches stay at ZERO under 8-writer fan-in and the
    table still sums exactly."""
    from brpc_tpu import obs
    import threading

    obs.set_enabled(True)  # earlier suites may leave obs off
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev,
                            combine=True)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    try:
        before = s.table
        wasted0 = obs.counter("ps_device_wasted_launches").get_value()
        ids = np.arange(8, dtype=np.int32)
        g = np.ones((8, DIM), np.float32)

        def writer():
            e = RemoteEmbedding([s.address], VOCAB, DIM,
                                timeout_ms=120000)
            try:
                for _ in range(3):
                    e.apply_gradients(ids, g)
            finally:
                e.close()

        threads = [threading.Thread(target=writer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        after = s.table
        # 8 writers x 3 rounds x lr 0.5 x ones = exactly -12.0
        np.testing.assert_allclose(after[:8], before[:8] - 12.0,
                                   rtol=1e-5)
        assert obs.counter("ps_device_wasted_launches").get_value() \
            == wasted0
        assert obs.counter("ps_combined_applies").get_value() > 0
    finally:
        emb.close()
        s.close()
        dev.close()


def test_device_stream_push_applies_through_combiner():
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev,
                            stream=True)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    try:
        before = s.table
        ids = np.array([2, 3, 3], np.int32)  # duplicate: must accumulate
        emb.push_gradients(ids, np.ones((3, DIM), np.float32))
        emb.flush_gradients()
        after = s.table
        np.testing.assert_allclose(after[2], before[2] - 0.5, rtol=1e-5)
        np.testing.assert_allclose(after[3], before[3] - 1.0, rtol=1e-5)
    finally:
        emb.close()
        s.close()
        dev.close()


# ---------------------------------------------------------------------------
# ISSUE 20 fault matrix: the device tier is a first-class citizen of
# the replication / fencing / checkpoint / migration machinery — the
# SAME scenarios test_replication.py / test_reshard.py / test_durable.py
# prove on the CPU tier, with the serving table resident in HBM.
# ---------------------------------------------------------------------------


def _device_pair(dev, **kw):
    """1 shard x 2 device replicas, replica 0 the boot primary (serving
    from HBM), replica 1 a backup folded down to its host mirror."""
    servers = [DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0,
                                   device_client=dev, **kw)
               for _ in range(2)]
    rs = ReplicaSet(tuple(sv.address for sv in servers), primary=0)
    for r, sv in enumerate(servers):
        sv.configure_replication(rs, r)
    return servers, rs


def _retry_policy(attempts=4, attempt_ms=500):
    return resilience.RetryPolicy(
        max_attempts=attempts,
        backoff=resilience.Backoff(base_ms=1, max_ms=10),
        attempt_timeout_ms=attempt_ms)


def _close_all(*servers):
    for sv in servers:
        sv.close()


def test_device_kill_primary_failover_zero_failed_lookups():
    """Kill the HBM-serving primary under sustained load: every lookup
    and write still succeeds (redirect + failover), the backup's host
    mirror is STAGED INTO HBM at promotion, and the revived ex-primary
    is fenced back to a host-mirror backup."""
    dev = _device_client()
    servers, rs = _device_pair(dev)
    emb = RemoteEmbedding(
        [rs], VOCAB, DIM, timeout_ms=10000, retry=_retry_policy(),
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=4, min_samples=2,
                                      min_isolation_ms=50),
            redirect=True),
        health_check=True, health_interval_ms=20)
    ids = np.arange(VOCAB, dtype=np.int32)
    grads = np.ones((VOCAB, DIM), np.float32)
    stages0 = int(obs.counter("ps_device_promote_stages").get_value())
    mirrors0 = int(obs.counter("ps_device_mirror_downs").get_value())
    try:
        assert servers[0]._dev_serving and not servers[1]._dev_serving
        emb.apply_gradients(ids, grads)      # warm: streams + replicas
        prim = servers[0].address
        fault.install(fault.FaultPlan(fault.kill_rules(prim), seed=3))
        # sustained load with the primary dead: every batch must
        # succeed — redirect + failover, never an exception
        t_end = time.monotonic() + 1.0
        reads = writes = 0
        while time.monotonic() < t_end:
            emb.lookup(ids)
            reads += 1
            emb.apply_gradients(ids, grads)
            writes += 1
        assert reads > 5 and writes > 5
        # the backup was promoted with a fencing epoch AND its mirror
        # was staged into HBM — it now serves the device path
        assert servers[1].is_primary and servers[1].epoch >= 1
        assert servers[1]._dev_serving
        assert int(obs.counter("ps_device_promote_stages").get_value()) \
            > stages0
        assert int(obs.counter("ps_client_failovers").get_value()) >= 1
        fault.clear()
        # the prober revives the corpse; the new primary's propagation
        # fences it into a BACKUP — which folds its HBM table down
        # into the host mirror (nothing device-applied is lost)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and emb._isolated(prim):
            time.sleep(0.02)
        assert not emb._isolated(prim)
        emb.apply_gradients(ids, grads)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and (servers[0].is_primary
                                               or servers[0]._dev_serving):
            time.sleep(0.02)
        assert not servers[0].is_primary
        assert not servers[0]._dev_serving
        assert int(obs.counter("ps_device_mirror_downs").get_value()) \
            > mirrors0
    finally:
        fault.clear()
        emb.close()
        _close_all(*servers)
        dev.close()


def test_device_fenced_stale_primary_rejected_and_mirrored_down():
    """An out-of-band promotion the HBM-serving primary never heard
    about: its next propagation is refused with EFENCED, the write is
    NOT acked, and the stale primary demotes itself — folding the live
    device table down into the host mirror."""
    dev = _device_client()
    servers, _ = _device_pair(dev)
    old, new = servers
    mirrors0 = int(obs.counter("ps_device_mirror_downs").get_value())
    try:
        # wait for the (eagerly connected) delta stream: the fence
        # notification rides its reply half
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not any(
                p.stream is not None and not p.need_sync
                for p in old._replicator._peers):
            time.sleep(0.01)
        # Partition the old primary's replication CONTROL plane so the
        # new primary cannot inform it (otherwise the eager propagation
        # demotes it instantly) — the old data stream stays up.
        fault.install(fault.FaultPlan([
            fault.FaultRule(action="error", side="server", service="Ps",
                            method="Sync", endpoint=old.address,
                            error_code=1009),
            fault.FaultRule(action="error", side="server", service="Ps",
                            method="ReplicaApply", endpoint=old.address,
                            error_code=1009)], seed=1))
        # Out-of-band promotion (epoch 1): stages the backup's host
        # mirror into HBM before the promote response lands.
        ch_new = rpc.Channel(new.address, timeout_ms=5000)
        try:
            ch_new.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch_new.close()
        assert new.is_primary and new.epoch == 1 and new._dev_serving
        assert old.is_primary            # stale, unaware, still on HBM
        ch_old = rpc.Channel(old.address, timeout_ms=5000)
        try:
            with pytest.raises(rpc.RpcError) as ei:
                ch_old.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                    np.arange(4, dtype=np.int32),
                    np.ones((4, DIM), np.float32))))
            assert ei.value.code == resilience.EFENCED
            # demoted: the next write is refused outright
            with pytest.raises(rpc.RpcError) as ei2:
                ch_old.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                    np.arange(4, dtype=np.int32),
                    np.ones((4, DIM), np.float32))))
            assert ei2.value.code == resilience.ENOTPRIMARY
        finally:
            ch_old.close()
        assert not old.is_primary
        # the fence demotion folded the device table into the mirror
        assert not old._dev_serving
        assert int(obs.counter("ps_device_mirror_downs").get_value()) \
            > mirrors0
        assert int(obs.counter("ps_replica_fenced").get_value()) >= 1
    finally:
        _close_all(*servers)
        dev.close()


def test_device_checkpoint_cold_restart_bit_exact(tmp_path):
    """Cold restart from the durable ledger: every delta the device
    primary ACKED is teed into the CheckpointStore, and a FRESH device
    server replays base + chain to the exact acked generation —
    byte-for-byte, through the HBM roundtrip."""
    dev = _device_client()
    sv = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3,
                             device_client=dev)
    store = CheckpointStore(str(tmp_path))
    emb = RemoteEmbedding([sv.address], VOCAB, DIM, timeout_ms=120000)
    ids = np.arange(VOCAB, dtype=np.int32)
    try:
        assert sv.attach_checkpoint(store) is None  # nothing to recover
        assert sv._dev_serving                      # re-staged after tee
        for d in (0.5, 0.25, 0.125):
            emb.apply_gradients(ids, np.full((VOCAB, DIM), d,
                                             np.float32))
        expect = sv.table.copy()
        gen = sv._install_gen
    finally:
        emb.close()
        sv.close()
        store.close()
    # cold restart: fresh process state, same store root
    sv2 = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3,
                              device_client=dev)
    store2 = CheckpointStore(str(tmp_path))
    try:
        point = sv2.attach_checkpoint(store2)
        assert point is not None and point.gen == gen
        assert sv2._install_gen == gen
        assert sv2._dev_serving                     # recovered AND serving
        assert np.array_equal(sv2.table, expect)    # bit-exact ledger
        # the gen-0 base was stamped seeded: it is a real snapshot of
        # the seeded table, not mistakable for a fresh one
        assert store2.load_base()[4]
        # the tee re-armed: device applies keep checkpointing
        emb2 = RemoteEmbedding([sv2.address], VOCAB, DIM,
                               timeout_ms=120000)
        try:
            emb2.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                              np.float32))
        finally:
            emb2.close()
        assert store2.last_gen == sv2._install_gen
    finally:
        sv2.close()
        store2.close()
        dev.close()


def test_device_split_severed_midcopy_recovers_byte_identical():
    """A LIVE 1→2 split off a device-serving source with the handoff
    plane of one destination severed mid-copy: the shipper backs off,
    reconnects, resyncs the range wholesale, and after cutover the
    destination DEVICE shards hold exactly the source's bytes — the
    generation-pinned device snapshot feeding unchanged MigrateSync
    framing."""
    dev = _device_client()
    src = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0,
                              device_client=dev)
    new = [DevicePsShardServer(VOCAB, DIM, s, 2, lr=1.0, importing=True,
                               scheme_version=1, device_client=dev)
           for s in range(2)]
    sc0 = PartitionScheme(0, (ReplicaSet.of(src.address),))
    sc1 = PartitionScheme(1, tuple(ReplicaSet.of(sv.address)
                                   for sv in new))
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = src.table.copy()
    drv = MigrationDriver(sc0, sc1, VOCAB)
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        # the first 3 handoff attempts at destination 1 die mid-stream
        fault.install(fault.FaultPlan(fault.partition_rules(
            new[1].address, max_hits=3), seed=5))
        drv.start()
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        drv.wait_caught_up(deadline_s=30)
        fault.clear()
        drv.cutover()
        # cutover's CompleteImport opened the destinations for
        # business: device primaries stage their imported mirrors
        # into HBM and serve the device path
        assert all(sv._dev_serving for sv in new)
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25, 0.125):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in new]), expect)
        assert int(obs.counter(
            "ps_migrate_connect_errors").get_value()) >= 1
    finally:
        fault.clear()
        drv.close()
        emb.close()
        _close_all(src, *new)
        dev.close()


def test_device_split_shipper_retargets_to_promoted_dest_backup():
    """Kill a REPLICATED destination's primary mid-split: the stranded
    shipper sweeps the destination replica group (``ReplicaState``,
    highest claiming epoch wins), re-points at the promoted backup and
    resyncs wholesale — ``ps_migration_retargets`` counts the save and
    the survivor converges byte-identical."""
    dev = _device_client()
    src = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=1.0,
                              device_client=dev)
    dst_a = DevicePsShardServer(VOCAB, DIM, 0, 2, lr=1.0,
                                importing=True, scheme_version=1,
                                device_client=dev)
    dst_b = DevicePsShardServer(VOCAB, DIM, 0, 2, lr=1.0,
                                importing=True, scheme_version=1,
                                device_client=dev)
    dst_1 = DevicePsShardServer(VOCAB, DIM, 1, 2, lr=1.0,
                                importing=True, scheme_version=1,
                                device_client=dev)
    rs0 = ReplicaSet((dst_a.address, dst_b.address), primary=0)
    dst_a.configure_replication(rs0, 0)
    dst_b.configure_replication(rs0, 1)
    sc0 = PartitionScheme(0, (ReplicaSet.of(src.address),))
    sc1 = PartitionScheme(1, (rs0, ReplicaSet.of(dst_1.address)))
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    retargets0 = int(obs.counter("ps_migration_retargets").get_value())
    drv = MigrationDriver(sc0, sc1, VOCAB)
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        drv.start()
        drv.wait_caught_up(deadline_s=30)   # initial copy lands
        # destination primary dies; the backup is promoted out-of-band
        # (the rebalancer's job) — the fixed spec address now strands
        # the shipper until the ReplicaState sweep re-points it
        fault.install(fault.FaultPlan(
            fault.kill_rules(dst_a.address), seed=7))
        ch = rpc.Channel(dst_b.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch.close()
        assert dst_b.is_primary
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and int(obs.counter(
                "ps_migration_retargets").get_value()) <= retargets0:
            time.sleep(0.02)
        assert int(obs.counter("ps_migration_retargets").get_value()) \
            > retargets0
        drv.wait_caught_up(deadline_s=30)
        # the promoted backup holds the source's exact bytes for its
        # range (wholesale resync: it never saw MigrateApply)
        half = VOCAB // 2
        src_now = src.table
        assert np.array_equal(dst_b.table, src_now[:half])
        assert np.array_equal(dst_1.table, src_now[half:])
    finally:
        fault.clear()
        drv.abort()
        drv.close()
        emb.close()
        _close_all(src, dst_a, dst_b, dst_1)
        dev.close()


def test_device_rebalancer_failback_restages_declared_primary():
    """The rebalancer's autonomous failback on the DEVICE tier: a
    usurped HBM-serving primary that came back as a host-mirror backup
    is promoted back once caught up — and the fenced Promote restages
    its mirror into HBM.  rebalance.py needs ZERO device knowledge:
    the same ReplicaState freshness gate and Promote wire call drive
    both tiers."""
    dev = _device_client()
    servers, rs = _device_pair(dev)
    declared, usurper = servers
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    reg_addr = f"127.0.0.1:{reg_server.start('127.0.0.1:0')}"
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", PartitionScheme(1, (rs,)))
    for sv in servers:
        nc.register("ps", sv.address, ttl_ms=500, tag_fn=sv.claim_tag)
    reb = Rebalancer(reg_addr, "ps", VOCAB,
                     policy=RebalancePolicy(RebalanceOptions(
                         failback_sustain_s=0.0)))
    ids = np.arange(8, dtype=np.int32)
    grads = np.full((8, DIM), 0.5, np.float32)
    try:
        # failure-style promotion of the backup: it stages to HBM
        ch = rpc.Channel(usurper.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
            assert usurper.is_primary and usurper._dev_serving
            # the declared primary learns it was usurped on the next
            # propagation — poke with a write so the fence lands
            ch.call("Ps", "ApplyGrad",
                    bytes(_pack_apply_req(ids, grads)))
        finally:
            ch.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and (declared.is_primary
                                               or declared._dev_serving):
            time.sleep(0.02)
        assert not declared.is_primary and not declared._dev_serving
        fb0 = int(obs.counter("ps_failbacks").get_value())
        decided = None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and decided is None:
            decided = reb.step()
            time.sleep(0.05)
        assert decided is not None and decided.kind == "failback"
        assert int(obs.counter("ps_failbacks").get_value()) == fb0 + 1
        # failed back AND serving from HBM again
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not (
                declared.is_primary and declared._dev_serving):
            time.sleep(0.02)
        assert declared.is_primary and declared._dev_serving
        assert declared.epoch >= 2
    finally:
        reb.stop()
        nc.close()
        _close_all(*servers)
        reg_server.close()
        dev.close()
