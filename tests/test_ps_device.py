"""Device-resident PS shard: the embedding table lives in HBM behind a
native buffer handle; Lookup/ApplyGrad are compiled gather/scatter-sub
launches and bytes ride the native staging fabric (no JAX in the serving
path). Skips when no PJRT plugin/device is reachable."""

import numpy as np
import pytest

from brpc_tpu import rpc
from brpc_tpu.ps_remote import DevicePsShardServer, RemoteEmbedding

VOCAB, DIM = 16, 8


import functools


@functools.lru_cache(maxsize=1)
def _axon_tunnel_alive() -> bool:
    # The axon plugin talks to a local relay; the relay's port being open is
    # NOT enough (a wedged tunnel accepts connects but blocks client init
    # forever), so probe by actually creating a device client in a child
    # process under a hard deadline. Cached: the tunnel state won't flip
    # mid-run, and the probe costs seconds.
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.settimeout(0.5)
    try:
        s.connect(("127.0.0.1", 8082))
    except OSError:
        return False
    finally:
        s.close()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "from brpc_tpu import rpc; rpc.DeviceClient().close(); "
             "print('ok')"],
            capture_output=True, text=True, timeout=60, cwd=repo_root)
    except (subprocess.TimeoutExpired, OSError):
        return False
    return proc.returncode == 0 and "ok" in proc.stdout


def _device_client():
    import os
    plugin = os.environ.get("BRT_PJRT_PLUGIN")
    if plugin is None and not _axon_tunnel_alive():
        # Deterministic fallback: the in-repo fake N-device plugin (same
        # one the native multi-replica tests use).
        fake = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "cpp", "build",
            "libbrt_fake_pjrt.so")
        if not os.path.exists(fake):
            fake = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "build", "libbrt_fake_pjrt.so")
        if os.path.exists(fake):
            plugin = fake
        else:
            pytest.skip("no PJRT plugin reachable (tunnel down, no fake)")
    try:
        return rpc.DeviceClient(plugin)
    except Exception as e:  # noqa: BLE001
        pytest.skip(f"no native PJRT device: {e}")


@pytest.fixture(scope="module")
def shard():
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    yield s, emb
    emb.close()
    s.close()
    dev.close()


# allow_handle_leak: the module-scoped `shard` fixture compiles its
# gather/scatter executables lazily inside these tests and caches them
# for the module's lifetime — net-per-test handle growth is the cache
# filling, released at fixture teardown, not a leak.
@pytest.mark.allow_handle_leak
def test_device_lookup_matches_resident_table(shard):
    s, emb = shard
    host = s.table  # DMA snapshot of the HBM-resident table
    ids = np.array([0, 3, 7, 15], np.int32)
    rows = emb.lookup(ids)
    np.testing.assert_allclose(rows, host[ids], rtol=1e-6)


@pytest.mark.allow_handle_leak  # same module-fixture exe-cache growth
def test_device_apply_grad_updates_hbm_table(shard):
    s, emb = shard
    before = s.table
    ids = np.array([1, 2, 5, 5], np.int32)  # duplicate: must accumulate
    grads = np.ones((4, DIM), np.float32)
    emb.apply_gradients(ids, grads)
    after = s.table
    np.testing.assert_allclose(after[1], before[1] - 0.5, rtol=1e-5)
    np.testing.assert_allclose(after[2], before[2] - 0.5, rtol=1e-5)
    # row 5 got BOTH contributions (scatter-add semantics on device)
    np.testing.assert_allclose(after[5], before[5] - 1.0, rtol=1e-5)
    # untouched rows stay put
    np.testing.assert_allclose(after[0], before[0], rtol=1e-6)


def test_device_training_step_roundtrip(shard):
    s, emb = shard
    ids = np.array([4, 6, 8, 9], np.int32)
    target = np.zeros((4, DIM), np.float32)
    first_loss = None
    for _ in range(5):
        rows = emb.lookup(ids)
        loss = float(((rows - target) ** 2).mean())
        if first_loss is None:
            first_loss = loss
        emb.apply_gradients(ids, rows - target)
    assert float(((emb.lookup(ids) - target) ** 2).mean()) < first_loss


def test_device_combiner_single_launch_no_wasted_scatters():
    """combine=True routes every ApplyGrad through the combiner: the
    lost-swap redo loop never races itself (one installer at a time), so
    wasted scatter launches stay at ZERO under 8-writer fan-in and the
    table still sums exactly."""
    from brpc_tpu import obs
    import threading

    obs.set_enabled(True)  # earlier suites may leave obs off
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev,
                            combine=True)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    try:
        before = s.table
        wasted0 = obs.counter("ps_device_wasted_launches").get_value()
        ids = np.arange(8, dtype=np.int32)
        g = np.ones((8, DIM), np.float32)

        def writer():
            e = RemoteEmbedding([s.address], VOCAB, DIM,
                                timeout_ms=120000)
            try:
                for _ in range(3):
                    e.apply_gradients(ids, g)
            finally:
                e.close()

        threads = [threading.Thread(target=writer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        after = s.table
        # 8 writers x 3 rounds x lr 0.5 x ones = exactly -12.0
        np.testing.assert_allclose(after[:8], before[:8] - 12.0,
                                   rtol=1e-5)
        assert obs.counter("ps_device_wasted_launches").get_value() \
            == wasted0
        assert obs.counter("ps_combined_applies").get_value() > 0
    finally:
        emb.close()
        s.close()
        dev.close()


def test_device_stream_push_applies_through_combiner():
    dev = _device_client()
    s = DevicePsShardServer(VOCAB, DIM, 0, 1, lr=0.5, device_client=dev,
                            stream=True)
    emb = RemoteEmbedding([s.address], VOCAB, DIM, timeout_ms=120000)
    try:
        before = s.table
        ids = np.array([2, 3, 3], np.int32)  # duplicate: must accumulate
        emb.push_gradients(ids, np.ones((3, DIM), np.float32))
        emb.flush_gradients()
        after = s.table
        np.testing.assert_allclose(after[2], before[2] - 0.5, rtol=1e-5)
        np.testing.assert_allclose(after[3], before[3] - 1.0, rtol=1e-5)
    finally:
        emb.close()
        s.close()
        dev.close()
