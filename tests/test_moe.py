"""MoE layer tests: routing correctness + expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np

from brpc_tpu.models import moe
from brpc_tpu.parallel import make_mesh, shard_params


def test_moe_forward_shapes_and_grads():
    cfg = moe.MoeConfig(hidden=32, intermediate=64, n_experts=4,
                        dtype=jnp.float32)
    params = moe.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32), jnp.float32)
    out, aux = jax.jit(lambda p, x: moe.moe_layer(p, x, cfg))(params, x)
    assert out.shape == x.shape
    assert jnp.isfinite(out).all()
    assert float(aux) > 0

    def loss(p):
        o, a = moe.moe_layer(p, x, cfg)
        return jnp.sum(o ** 2) + 0.01 * a

    grads = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert bool(jnp.isfinite(leaf).all())


def test_moe_capacity_overflow_passthrough():
    # capacity so small most tokens drop: output far smaller than input norm
    cfg = moe.MoeConfig(hidden=16, intermediate=32, n_experts=2,
                        capacity_factor=0.1, dtype=jnp.float32)
    params = moe.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 64, 16), jnp.float32)
    out, _ = moe.moe_layer(params, x, cfg)
    assert out.shape == x.shape  # dropped tokens produce zeros, no crash


def test_moe_expert_parallel_matches_single_device():
    cfg = moe.MoeConfig(hidden=32, intermediate=64, n_experts=4,
                        dtype=jnp.float32)
    params = moe.init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 16, 32), jnp.float32)
    want, want_aux = moe.moe_layer(params, x, cfg)

    mesh = make_mesh({"ep": 4})
    sharded = shard_params(params, moe.moe_param_specs(), mesh)
    got, got_aux = jax.jit(
        lambda p, x: moe.moe_layer(p, x, cfg))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(float(got_aux), float(want_aux), rtol=1e-5)
