"""Zero-copy buffer currency (brt_iobuf): the borrow-not-copy contract.

Covers the capi family end to end from Python: building chains from
owned headers + borrowed (pinned) payloads, the exact pin/handle
ledgers (Python analysis ledger vs the native ground-truth counts),
the borrow-lifetime rule (a view exported from a chain stays valid
after ``close()`` — destruction defers to the last view's death), the
call/respond iobuf variants riding a real server, batched
``Stream.writev``, and runtime byte-parity of every refactored iobuf
packer against its wire schema (the dynamic twin of the wire-contract
lint, proving the borrow path changes NOTHING on the wire)."""

import gc
import struct
import time

import numpy as np
import pytest

from brpc_tpu import obs, rpc, wire
from brpc_tpu.analysis import fuzz, handles
from brpc_tpu.ps_remote import (_pack_apply_req, _pack_apply_req_iobuf,
                                _pack_deadline, _pack_deadline_iobuf,
                                _pack_deadline_rel,
                                _pack_deadline_rel_iobuf,
                                _pack_lookup_req, _pack_lookup_req_iobuf,
                                _pack_stream_frame,
                                _pack_stream_frame_iobuf)

pytestmark = pytest.mark.needs_native


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _native_iobufs() -> int:
    return rpc.debug_handle_counts().get("iobuf", 0)


def _settle(baseline_fn, want, deadline_s=5.0):
    """Finalizers and native release callbacks may run a beat late."""
    deadline = time.time() + deadline_s
    while baseline_fn() != want and time.time() < deadline:
        gc.collect()
        time.sleep(0.01)
    return baseline_fn()


# ---------------------------------------------------------------------------
# chain building + ledgers
# ---------------------------------------------------------------------------

def test_iobuf_build_owned_and_borrowed_roundtrip():
    header = b"\x01\x02\x03\x04"
    payload = np.arange(64, dtype=np.int32)
    io = rpc.IOBuf()
    io.append(header)                 # owned copy (framing header)
    io.append_pinned(payload)         # borrowed, no copy
    assert len(io) == len(header) + payload.nbytes
    assert io.block_count >= 2
    assert io.tobytes() == header + payload.tobytes()

    # block-sharing append: no payload copy, same bytes
    outer = rpc.IOBuf(b"hdr2")
    outer.append_iobuf(io)
    assert outer.tobytes() == b"hdr2" + header + payload.tobytes()
    io.close()
    # outer's shared blocks survive the inner handle's death
    assert outer.tobytes() == b"hdr2" + header + payload.tobytes()
    outer.close()
    with pytest.raises(RuntimeError):
        io.append(b"closed")


def test_iobuf_ledger_python_native_parity():
    gc.collect()
    py0 = handles.live_counts().get("iobuf", 0)
    nat0 = _native_iobufs()
    ios = [rpc.IOBuf(b"x" * (i + 1)) for i in range(5)]
    assert handles.live_counts().get("iobuf", 0) == py0 + 5
    assert _native_iobufs() == nat0 + 5
    # the two ledgers must agree while live and after release
    assert (handles.live_counts().get("iobuf", 0) - py0
            == _native_iobufs() - nat0)
    for io in ios:
        io.close()
    assert handles.live_counts().get("iobuf", 0) == py0
    assert _settle(_native_iobufs, nat0) == nat0


def test_pinned_buffer_released_with_handle():
    pins0 = rpc.debug_iobuf_pins()
    arr = np.full(1024, 7, np.int64)
    io = rpc.IOBuf()
    io.append_pinned(arr)
    assert rpc.debug_iobuf_pins() == pins0 + 1
    # the pin is the keepalive: the chain reads the live buffer
    assert io.tobytes() == arr.tobytes()
    io.close()
    assert _settle(rpc.debug_iobuf_pins, pins0) == pins0


# ---------------------------------------------------------------------------
# borrow lifetime: views never dangle
# ---------------------------------------------------------------------------

def test_view_outlives_close():
    gc.collect()
    nat0 = _native_iobufs()
    io = rpc.IOBuf(b"borrow-me")      # single block: zero-copy view
    view = io.as_memoryview()
    io.close()
    # the live view defers the handle's destruction...
    assert _native_iobufs() == nat0 + 1
    # ...and still reads valid native memory
    assert bytes(view) == b"borrow-me"
    del view
    assert _settle(_native_iobufs, nat0) == nat0


def test_response_view_outlives_the_call():
    srv = rpc.Server()
    srv.add_service("Echo", lambda m, req: req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    try:
        # force_iobuf: sub-floor payloads normally reroute to the bytes
        # twin — the escape hatch keeps the native path under test.
        req = rpc.IOBuf(b"tiny-response", force_iobuf=True)
        rsp = ch.call("Echo", "Echo", req)
        req.close()
        assert isinstance(rsp, rpc.IOBuf)
        view = rsp.as_memoryview()
        rsp.close()                   # view keeps the blocks pinned
        assert bytes(view) == b"tiny-response"
        del view
    finally:
        ch.close()
        srv.close()


# ---------------------------------------------------------------------------
# call/respond iobuf variants against a live server
# ---------------------------------------------------------------------------

def test_echo_call_iobuf_roundtrip_and_copy_ledger():
    payload = np.random.default_rng(0).bytes(32 * 1024)
    srv = rpc.Server()

    def echo(method, request):
        rsp = rpc.IOBuf()
        rsp.append_pinned(request)    # respond shares, never copies
        return rsp
    srv.add_service("Echo", echo)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    gc.collect()
    nat0 = _native_iobufs()
    pins0 = rpc.debug_iobuf_pins()
    try:
        c0 = int(obs.counter("rpc_bytes_copied").get_value())
        req = rpc.IOBuf()
        req.append_pinned(payload)
        rsp = ch.call("Echo", "Echo", req)
        try:
            assert rsp.tobytes() == payload
        finally:
            rsp.close()
            req.close()
        copied = int(obs.counter("rpc_bytes_copied").get_value()) - c0
        # the only counted copies: the server trampoline materializing
        # the request for the Python handler, and our own tobytes()
        # verification readback — the transport itself borrowed
        assert copied == 2 * len(payload)
    finally:
        ch.close()
        srv.close()
    assert _settle(_native_iobufs, nat0) == nat0
    assert _settle(rpc.debug_iobuf_pins, pins0) == pins0


def test_call_async_join_iobuf():
    srv = rpc.Server()
    srv.add_service("Echo", lambda m, req: req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    try:
        reqs = [rpc.IOBuf(struct.pack("<q", i), force_iobuf=True)
                for i in range(4)]
        pending = [ch.call_async("Echo", "Echo", r) for r in reqs]
        for i, p in enumerate(pending):
            rsp = p.join()
            assert isinstance(rsp, rpc.IOBuf)
            with rsp:
                assert rsp.tobytes() == struct.pack("<q", i)
        for r in reqs:
            r.close()
    finally:
        ch.close()
        srv.close()


def test_small_iobuf_routes_through_bytes_twin():
    """PR-19 residue closed: explicit IOBuf payloads below
    ``rpc.IOBUF_MIN_BYTES`` ride the bytes twin automatically — the
    response is byte-identical, arrives as plain bytes, and no native
    iobuf handle is spent on the wire leg; ``force_iobuf=True`` opts
    back into the native path; at-floor payloads keep it."""
    srv = rpc.Server()

    def echo_io(method, request):
        out = rpc.IOBuf()            # respond path: also auto-routed
        out.append(request)
        return out
    srv.add_service("Echo", echo_io)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    try:
        small = b"s" * (rpc.IOBUF_MIN_BYTES - 1)
        big = b"b" * rpc.IOBUF_MIN_BYTES
        req = rpc.IOBuf(small)
        rsp = ch.call("Echo", "Echo", req)
        req.close()
        assert isinstance(rsp, bytes) and rsp == small   # byte parity
        req = rpc.IOBuf(small)
        rsp = ch.call_async("Echo", "Echo", req).join()
        req.close()
        assert isinstance(rsp, bytes) and rsp == small
        req = rpc.IOBuf(small, force_iobuf=True)
        rsp = ch.call("Echo", "Echo", req)
        req.close()
        assert isinstance(rsp, rpc.IOBuf)
        with rsp:
            assert rsp.tobytes() == small
        req = rpc.IOBuf(big)         # at the floor: native path kept
        rsp = ch.call("Echo", "Echo", req)
        req.close()
        assert isinstance(rsp, rpc.IOBuf)
        with rsp:
            assert rsp.tobytes() == big
    finally:
        ch.close()
        srv.close()


# ---------------------------------------------------------------------------
# batched stream writes
# ---------------------------------------------------------------------------

def test_stream_writev_frames_arrive_intact_and_ordered():
    frames_in = []
    closed = []

    class Sink:
        def on_data(self, data):
            frames_in.append(bytes(data))

        def on_closed(self):
            closed.append(True)

    srv = rpc.Server()

    def h(method, request, accept):
        accept(Sink())
        return b"ok"
    srv.add_stream_handler("Push", h)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=5000)
    gc.collect()
    nat0 = _native_iobufs()
    pins0 = rpc.debug_iobuf_pins()
    try:
        st = ch.stream("Push", "Open")
        body = np.arange(256, dtype=np.float32)
        expect = []
        batch = []
        for seq in range(3):
            io = _pack_stream_frame_iobuf(seq, 0, 0, body.tobytes())
            batch.append(io)
            expect.append(bytes(_pack_stream_frame(seq, 0, 0,
                                                   body.tobytes())))
        batch.append(b"raw-bytes-frame")   # mixed batch: bytes get pinned
        expect.append(b"raw-bytes-frame")
        assert st.writev(batch[:2]) == 2
        assert st.writev(batch[2:]) == 2
        for io in batch[:3]:
            io.close()
        st.close()
        deadline = time.time() + 5
        while not closed and time.time() < deadline:
            time.sleep(0.01)
        assert closed, "stream close handshake never completed"
        assert frames_in == expect
    finally:
        ch.close()
        srv.close()
    assert _settle(_native_iobufs, nat0) == nat0
    assert _settle(rpc.debug_iobuf_pins, pins0) == pins0


# ---------------------------------------------------------------------------
# wire parity: the borrow path changes NOTHING on the wire
# ---------------------------------------------------------------------------

def _materialized(pack_fn):
    """parity_fuzz adapter: run an iobuf builder, hand back its bytes."""
    def packer(values):
        io = pack_fn(values)
        try:
            return io.tobytes()
        finally:
            io.close()
    return packer


def test_parity_fuzz_lookup_req_iobuf():
    sch = wire.REGISTRY["lookup_req"]
    failures = fuzz.parity_fuzz(
        sch,
        _materialized(lambda v: _pack_lookup_req_iobuf(
            np.asarray(v["ids"], np.int32))),
        lambda p: np.frombuffer(
            p, np.int32, struct.unpack_from("<i", p, 0)[0], 4),
        seed=11, iters=30)
    assert failures == [], [f.detail for f in failures]
    # and the iobuf framing is byte-identical to the bytearray packer
    ids = np.arange(17, dtype=np.int32)
    io = _pack_lookup_req_iobuf(ids)
    with io:
        assert io.tobytes() == bytes(_pack_lookup_req(ids))


def test_parity_fuzz_apply_req_iobuf():
    sch = wire.REGISTRY["apply_req"]

    def unpack(p):
        (count,) = struct.unpack_from("<i", p, 0)
        ids = np.frombuffer(p, np.int32, count, 4)
        grads = np.frombuffer(p, np.float32, count * 4, 4 + 4 * count)
        return ids, grads

    failures = fuzz.parity_fuzz(
        sch,
        _materialized(lambda v: _pack_apply_req_iobuf(
            np.asarray(v["ids"], np.int32),
            np.asarray(v["grads"], np.float32))),
        unpack, seed=12, iters=30, dim=4)
    assert failures == [], [f.detail for f in failures]
    ids = np.arange(9, dtype=np.int32)
    grads = np.full((9, 4), 0.25, np.float32)
    io = _pack_apply_req_iobuf(ids, grads)
    with io:
        assert io.tobytes() == bytes(_pack_apply_req(ids, grads))


def test_parity_fuzz_stream_frame_iobuf():
    sch = wire.REGISTRY["stream_frame"]
    failures = fuzz.parity_fuzz(
        sch,
        _materialized(lambda v: _pack_stream_frame_iobuf(
            v["seq"], v["epoch"], v["gen"], v["body"])),
        lambda p: struct.unpack_from("<qqq", p, 0),
        seed=13, iters=30)
    assert failures == [], [f.detail for f in failures]


def test_deadline_iobuf_byte_parity():
    """The deadline schemas carry a fixed magic the schema fuzzer
    randomizes, so parity here is direct: both header forms, as a
    prepended block over borrowed bodies, must be byte-identical to
    the re-copying bytearray packers."""
    rng = np.random.default_rng(5)
    for _ in range(20):
        body = rng.bytes(int(rng.integers(0, 4096)))
        us = int(rng.integers(0, 1 << 60))
        io = _pack_deadline_iobuf(us, body)
        with io:
            assert io.tobytes() == bytes(_pack_deadline(us, body))
        io = _pack_deadline_rel_iobuf(us, body)
        with io:
            assert io.tobytes() == bytes(_pack_deadline_rel(us, body))
    # and block-sharing over an IOBuf body, not just bytes
    inner = rpc.IOBuf(b"inner-body")
    io = _pack_deadline_iobuf(123, inner)
    with io:
        assert io.tobytes() == bytes(_pack_deadline(123, b"inner-body"))
    inner.close()
