"""Parameter-server fabric + checkpoint tests (8-device CPU mesh)."""

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from brpc_tpu import ps
from brpc_tpu.models import llama
from brpc_tpu.parallel import make_mesh, shard_batch, shard_params
from brpc_tpu.utils import latest_step, restore_checkpoint, save_checkpoint


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"ps": 4})


def test_lookup_matches_dense(mesh):
    emb = ps.create_embedding(jax.random.PRNGKey(0), 64, 16, mesh, "ps")
    ids = jnp.array([[0, 5, 17], [63, 32, 5]], jnp.int32)
    got = jax.jit(lambda e, i: ps.lookup(e, i, mesh),
                  static_argnums=())(emb, ids)
    want = np.asarray(emb.table)[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_apply_gradients_touches_only_hit_rows(mesh):
    emb = ps.create_embedding(jax.random.PRNGKey(1), 32, 8, mesh, "ps")
    before = np.asarray(emb.table).copy()
    ids = jnp.array([3, 17, 31], jnp.int32)
    grads = jnp.ones((3, 8), jnp.float32)
    emb2 = ps.apply_gradients(emb, ids, grads, mesh, lr=0.5)
    after = np.asarray(emb2.table)
    hit = {3, 17, 31}
    for r in range(32):
        if r in hit:
            np.testing.assert_allclose(after[r], before[r] - 0.5, rtol=1e-6)
        else:
            np.testing.assert_allclose(after[r], before[r], rtol=1e-6)


def test_ps_train_step_reduces_loss(mesh):
    emb = ps.create_embedding(jax.random.PRNGKey(2), 64, 8, mesh, "ps")
    step = jax.jit(ps.make_ps_train_step("ps", "dp", mesh, lr=0.5))
    ids = jax.random.randint(jax.random.PRNGKey(3), (4, 8), 0, 64)
    targets = jax.random.normal(jax.random.PRNGKey(4), (4, 8, 8)) * 0.1
    _, loss0 = step(emb, ids, targets)
    for _ in range(20):
        emb, loss = step(emb, ids, targets)
    assert float(loss) < float(loss0)


def test_checkpoint_roundtrip(tmp_path):
    mesh = make_mesh({"tp": 2})
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    params = shard_params(params, llama.param_specs(cfg), mesh)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    ckpt = str(tmp_path / "ckpt")
    state = {"params": params, "step": jnp.int32(7)}
    save_checkpoint(ckpt, 7, state)
    assert latest_step(ckpt) == 7

    restored = restore_checkpoint(ckpt, template=state)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(restored["params"])
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert int(restored["step"]) == 7

    # resume: newer step wins
    save_checkpoint(ckpt, 9, {"params": params, "step": jnp.int32(9)})
    assert latest_step(ckpt) == 9
