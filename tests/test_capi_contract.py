"""C-ABI drift guard: the native header and the ctypes table must agree.

Every ``brt_*`` function declared in ``cpp/capi/c_api.h`` needs BOTH
``argtypes`` and ``restype`` declared in ``rpc._load()`` (ctypes defaults
an undeclared restype to c_int, which truncates 64-bit pointers/handles),
and every binding must point at a symbol the header still declares.

This complements the ``ctypes-contract`` lint check, which only sees the
Python side — a native symbol that was never bound at all is invisible to
it.  Parsing the header catches the gap, for the ``brt_ps_*`` /
call-group families and every future addition.  Pure text analysis: runs
without the native toolchain."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(ROOT, "cpp", "capi", "c_api.h")
BINDINGS = os.path.join(ROOT, "brpc_tpu", "rpc.py")


def _header_symbols():
    with open(HEADER, "r", encoding="utf-8") as f:
        src = f.read()
    src = re.sub(r"//[^\n]*", "", src)        # comments don't declare
    names = set(re.findall(r"\b(brt_\w+)\s*\(", src))
    # function-POINTER typedefs (callback types) are not callable symbols
    typedefs = set(re.findall(r"\(\s*\*\s*(brt_\w+)\s*\)", src))
    return names - typedefs


def _binding_decls():
    with open(BINDINGS, "r", encoding="utf-8") as f:
        src = f.read()
    decls = {}
    for name, kind in re.findall(
            r"lib\.(brt_\w+)\.(argtypes|restype)\s*=", src):
        decls.setdefault(name, set()).add(kind)
    return decls


def test_header_parses_to_a_plausible_symbol_set():
    symbols = _header_symbols()
    assert len(symbols) > 30                   # the ABI is not tiny
    assert "brt_channel_call" in symbols
    assert "brt_ps_shard_install" in symbols   # this PR's additions
    assert "brt_call_group_wait_any" in symbols
    assert "brt_service_handler" not in symbols  # typedef, not a symbol


def test_every_header_symbol_has_full_ctypes_binding():
    decls = _binding_decls()
    missing = []
    for name in sorted(_header_symbols()):
        gap = {"argtypes", "restype"} - decls.get(name, set())
        if gap:
            missing.append(f"{name} (missing {', '.join(sorted(gap))})")
    assert not missing, (
        "c_api.h declares symbols without a complete ctypes binding in "
        "rpc._load() — an undeclared restype truncates 64-bit handles:\n  "
        + "\n  ".join(missing))


def test_no_binding_for_a_symbol_the_header_dropped():
    header = _header_symbols()
    stale = sorted(n for n in _binding_decls() if n not in header)
    assert not stale, (
        f"rpc._load() binds symbols c_api.h no longer declares: {stale}")


# ---------------------------------------------------------------------------
# cpp-side constructor/destructor + handle-ledger symmetry
# ---------------------------------------------------------------------------
# The no-toolchain native lint fallback (the ROADMAP clang-tidy deferral
# stays honest): every `brt_*_new` DEFINED in the capi TUs must have its
# `_destroy`, and both must bump the native handle ledger
# (handle_inc/handle_dec) so brt_debug_handle_counts stays ground truth.
# Pure text analysis over cpp/capi/*.cc — no clang binary required.

CAPI_DIR = os.path.join(ROOT, "cpp", "capi")

#: constructor symbols that don't follow the _new naming rule, and the
#: destroy symbol owning their handle kind (mirrors the lint's
#: _ABI_NEW_PAIRS table)
_IRREGULAR_PAIRS = {
    "brt_channel_call_start_opts": "brt_call_destroy",
    "brt_device_compile": "brt_device_executable_destroy",
    "brt_channel_call_iobuf": "brt_iobuf_destroy",
    "brt_call_join_iobuf": "brt_iobuf_destroy",
    "brt_channel_call_start_iobuf": "brt_call_destroy",
}


def _capi_sources():
    out = {}
    for fname in sorted(os.listdir(CAPI_DIR)):
        if fname.endswith(".cc"):
            with open(os.path.join(CAPI_DIR, fname), "r",
                      encoding="utf-8") as f:
                out[fname] = f.read()
    return out


def _function_bodies(src: str):
    """symbol -> body text for top-level C function definitions, by
    brace balancing from each definition header."""
    out = {}
    for m in re.finditer(r"^(?:void\*?|char\*|long|int|int64_t)\s+"
                         r"(brt_\w+)\s*\([^;]*?\)\s*\{",
                         src, re.MULTILINE | re.DOTALL):
        name = m.group(1)
        depth, i = 1, m.end()
        while i < len(src) and depth:
            if src[i] == "{":
                depth += 1
            elif src[i] == "}":
                depth -= 1
            i += 1
        out[name] = src[m.end():i]
    return out


def _strip_line_comments(src: str) -> str:
    """Remove ``//`` comments without eating string literals that
    contain ``//`` (``a.find("://")`` must survive — a naive regex
    truncates the line mid-string and corrupts brace balance)."""
    out_lines = []
    for line in src.split("\n"):
        pos = 0
        while True:
            idx = line.find("//", pos)
            if idx < 0:
                break
            if line.count('"', 0, idx) % 2 == 0:
                line = line[:idx]
                break
            pos = idx + 2
        out_lines.append(line)
    return "\n".join(out_lines)


def _all_capi_bodies():
    bodies = {}
    for fname, src in _capi_sources().items():
        clean = _strip_line_comments(src)
        for name, body in _function_bodies(clean).items():
            bodies[name] = (fname, body)
    return bodies


def test_every_capi_constructor_has_its_destroy():
    bodies = _all_capi_bodies()
    news = [n for n in bodies if n.endswith("_new")]
    assert len(news) >= 5          # server/channel/event/group/ps_shard
    missing = []
    for name in sorted(news):
        expected = name[:-len("_new")] + "_destroy"
        if expected not in bodies:
            missing.append(f"{name} -> {expected}")
    for ctor, dtor in _IRREGULAR_PAIRS.items():
        if ctor in bodies and dtor not in bodies:
            missing.append(f"{ctor} -> {dtor}")
    assert not missing, (
        "capi constructors without a destroy in cpp/capi/*.cc — "
        "handles of these kinds cannot be freed:\n  "
        + "\n  ".join(missing))


def test_every_capi_pair_bumps_the_handle_ledger():
    """Both halves of every pair must feed the native ledger: a
    constructor that skips handle_inc (or a destroy that skips
    handle_dec) silently un-grounds the Python-vs-native ledger
    cross-check (brt_debug_handle_counts)."""
    bodies = _all_capi_bodies()
    pairs = [(n, n[:-len("_new")] + "_destroy")
             for n in bodies if n.endswith("_new")]
    pairs += [(c, d) for c, d in _IRREGULAR_PAIRS.items()
              if c in bodies]
    bad = []
    for ctor, dtor in sorted(pairs):
        if "handle_inc(" not in bodies[ctor][1]:
            bad.append(f"{ctor} ({bodies[ctor][0]}): no handle_inc")
        if dtor in bodies and "handle_dec(" not in bodies[dtor][1]:
            bad.append(f"{dtor} ({bodies[dtor][0]}): no handle_dec")
    assert not bad, (
        "capi constructor/destroy bodies not feeding the native handle "
        "ledger:\n  " + "\n  ".join(bad))
