"""C-ABI drift guard: the native header and the ctypes table must agree.

Every ``brt_*`` function declared in ``cpp/capi/c_api.h`` needs BOTH
``argtypes`` and ``restype`` declared in ``rpc._load()`` (ctypes defaults
an undeclared restype to c_int, which truncates 64-bit pointers/handles),
and every binding must point at a symbol the header still declares.

This complements the ``ctypes-contract`` lint check, which only sees the
Python side — a native symbol that was never bound at all is invisible to
it.  Parsing the header catches the gap, for the ``brt_ps_*`` /
call-group families and every future addition.  Pure text analysis: runs
without the native toolchain."""

import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(ROOT, "cpp", "capi", "c_api.h")
BINDINGS = os.path.join(ROOT, "brpc_tpu", "rpc.py")


def _header_symbols():
    with open(HEADER, "r", encoding="utf-8") as f:
        src = f.read()
    src = re.sub(r"//[^\n]*", "", src)        # comments don't declare
    names = set(re.findall(r"\b(brt_\w+)\s*\(", src))
    # function-POINTER typedefs (callback types) are not callable symbols
    typedefs = set(re.findall(r"\(\s*\*\s*(brt_\w+)\s*\)", src))
    return names - typedefs


def _binding_decls():
    with open(BINDINGS, "r", encoding="utf-8") as f:
        src = f.read()
    decls = {}
    for name, kind in re.findall(
            r"lib\.(brt_\w+)\.(argtypes|restype)\s*=", src):
        decls.setdefault(name, set()).add(kind)
    return decls


def test_header_parses_to_a_plausible_symbol_set():
    symbols = _header_symbols()
    assert len(symbols) > 30                   # the ABI is not tiny
    assert "brt_channel_call" in symbols
    assert "brt_ps_shard_install" in symbols   # this PR's additions
    assert "brt_call_group_wait_any" in symbols
    assert "brt_service_handler" not in symbols  # typedef, not a symbol


def test_every_header_symbol_has_full_ctypes_binding():
    decls = _binding_decls()
    missing = []
    for name in sorted(_header_symbols()):
        gap = {"argtypes", "restype"} - decls.get(name, set())
        if gap:
            missing.append(f"{name} (missing {', '.join(sorted(gap))})")
    assert not missing, (
        "c_api.h declares symbols without a complete ctypes binding in "
        "rpc._load() — an undeclared restype truncates 64-bit handles:\n  "
        + "\n  ".join(missing))


def test_no_binding_for_a_symbol_the_header_dropped():
    header = _header_symbols()
    stale = sorted(n for n in _binding_decls() if n not in header)
    assert not stale, (
        f"rpc._load() binds symbols c_api.h no longer declares: {stale}")
