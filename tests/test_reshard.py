"""Elastic resharding: dual-scheme routing + zero-downtime shard
splits (ISSUE 10).

Covers the tentpole end to end on real servers:

- PartitionScheme as a first-class versioned object (json roundtrip,
  row-range map, registry publication/parsing, claim tags);
- a LIVE 2→4 split under concurrent lookup+push load with ZERO failed
  lookups, exact-arithmetic zero-lost-acked-updates, and retirement
  proven by the native handle ledger;
- the idempotent unary write window (``ApplyGradId``): a
  timed-out-but-applied attempt's retry is dropped server-side, and a
  scheme GUARD drops a re-split delta that already migrated;
- migration under fault: the handoff stream severed mid-copy (resync
  recovers, byte-identical), a dead destination (cutover refuses, the
  old scheme keeps serving, abort leaves everything intact), and a
  stale-scheme writer racing the cutover (registry-driven refresh,
  exactly-once);
- primary/epoch claims published through the registry heartbeat:
  failover ADOPTS the claimed primary instead of sweeping.
"""

import json
import struct
import threading
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience, rpc
from brpc_tpu.naming import (NamingClient, PartitionScheme, ReplicaSet,
                             parse_claim_tag, parse_claims,
                             parse_schemes, parse_shard_tag,
                             publish_scheme, shard_tag)
from brpc_tpu.ps_remote import (PsShardServer, RemoteEmbedding,
                                _pack_apply_id_req, _pack_apply_req)
from brpc_tpu.reshard import MigrationDriver

pytestmark = pytest.mark.needs_native

VOCAB, DIM = 256, 8


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)
    fault.clear()


def _servers(num, lr=1.0, version=0, importing=False, **kw):
    return [PsShardServer(VOCAB, DIM, s, num, lr=lr, stream=True,
                          importing=importing, scheme_version=version,
                          **kw)
            for s in range(num)]


def _scheme(servers, version, **kw):
    return PartitionScheme(
        version, tuple(ReplicaSet.of(sv.address) for sv in servers),
        **kw)


def _retry_policy(attempts=4, attempt_ms=500):
    return resilience.RetryPolicy(
        max_attempts=attempts,
        backoff=resilience.Backoff(base_ms=1, max_ms=10),
        attempt_timeout_ms=attempt_ms)


def _close_all(*groups):
    for g in groups:
        for sv in g:
            sv.close()


# ---------------------------------------------------------------------------
# scheme objects + registry records
# ---------------------------------------------------------------------------

def test_partition_scheme_roundtrip_and_validation():
    sc = PartitionScheme(2, (ReplicaSet.of("a:1"),
                             ReplicaSet.of(["b:1", "b:2"])),
                         weight=0.5, state="draining",
                         bounds=(0, 100, 256))
    back = PartitionScheme.from_json(sc.to_json())
    assert back == sc
    assert back.num_shards == 2
    assert back.shard_bounds(0, 256) == (0, 100)
    assert back.shard_bounds(1, 256) == (100, 256)
    # uniform bounds without an explicit map
    uni = PartitionScheme(0, (ReplicaSet.of("a:1"),
                              ReplicaSet.of("a:2")))
    assert uni.shard_bounds(1, 256) == (128, 256)
    assert uni.with_(weight=0.0, state="retired").state == "retired"
    with pytest.raises(ValueError):
        PartitionScheme(0, ())
    with pytest.raises(ValueError):
        PartitionScheme(0, (ReplicaSet.of("a:1"),), state="nope")
    with pytest.raises(ValueError):
        PartitionScheme(0, (ReplicaSet.of("a:1"),), bounds=(5, 10))


def test_claim_tags_roundtrip():
    assert shard_tag(1, 4, 0, epoch=3, primary=True) == "1/4@e3P"
    assert shard_tag(1, 4, 2, epoch=0, primary=False) == "1/4/2@e0B"
    assert shard_tag(1, 4, 0, epoch=3, primary=True, scheme=7) \
        == "1/4@v7e3P"
    # claim-unaware resolvers still parse the shard part
    assert parse_shard_tag("1/4@e3P") == (1, 4, 0)
    assert parse_shard_tag("1/4/2@e0B") == (1, 4, 2)
    assert parse_shard_tag("1/4@v7e3P") == (1, 4, 0)
    # legacy claims parse with scheme=None; scoped ones carry it
    assert parse_claim_tag("1/4@e3P") == (1, 4, 0, 3, True, None)
    assert parse_claim_tag("1/4/2@e0B") == (1, 4, 2, 0, False, None)
    assert parse_claim_tag("1/4@v7e3P") == (1, 4, 0, 3, True, 7)
    assert parse_claim_tag("1/4") is None
    assert parse_claim_tag("1/4@zzz") is None
    assert parse_claim_tag("1/4@vxe3P") is None
    assert parse_claim_tag("1/4@v7") is None


def test_parse_schemes_and_claims_from_nodes():
    from brpc_tpu.naming import SCHEME_TAG_PREFIX, scheme_record_addr
    sc0 = PartitionScheme(0, (ReplicaSet.of("a:1"),))
    sc0b = sc0.with_(state="draining", weight=0.0)
    rec = scheme_record_addr(0)
    assert rec == "0.0.0.0:0"
    nodes = [
        {"addr": rec, "tag": SCHEME_TAG_PREFIX + sc0.to_json()},
        {"addr": "a:1", "tag": "0/1@e2P"},
        {"addr": "a:2", "tag": "0/1/1@e2B"},
        {"addr": rec, "tag": SCHEME_TAG_PREFIX + sc0b.to_json()},
        {"addr": "junk", "tag": "not-a-scheme"},
    ]
    schemes = parse_schemes(nodes)
    assert schemes[0].state == "draining"      # last occurrence wins
    claims = parse_claims(nodes)
    assert claims[(None, 1, 0)] == (2, "a:1")  # primary claim only
    with pytest.raises(ValueError):
        scheme_record_addr(70000)


def test_scheme_server_gates():
    """Importing destinations answer EMIGRATING; fenced sources answer
    ESCHEMEMOVED (writes) but keep serving reads."""
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, stream=True)
    dst = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, stream=True,
                        importing=True, scheme_version=1)
    ids = np.arange(4, dtype=np.int32)
    req = bytes(_pack_apply_req(ids, np.ones((4, DIM), np.float32)))
    lreq = struct.pack("<i", 4) + ids.tobytes()
    ch_d = rpc.Channel(dst.address, timeout_ms=5000)
    ch_s = rpc.Channel(sv.address, timeout_ms=5000)
    try:
        for method, payload in (("Lookup", lreq), ("ApplyGrad", req)):
            with pytest.raises(rpc.RpcError) as ei:
                ch_d.call("Ps", method, payload)
            assert ei.value.code == resilience.EMIGRATING
        # fence the source: writes redirect, reads keep serving
        ch_s.call("Ps", "SchemeFence", struct.pack("<q", 1))
        with pytest.raises(rpc.RpcError) as ei:
            ch_s.call("Ps", "ApplyGrad", req)
        assert ei.value.code == resilience.ESCHEMEMOVED
        assert len(ch_s.call("Ps", "Lookup", lreq)) == 4 * DIM * 4
        info = json.loads(ch_s.call("Ps", "SchemeInfo", b""))
        assert info["fenced"] and info["next_scheme"] == 1
    finally:
        ch_d.close()
        ch_s.close()
        _close_all([sv, dst])


# ---------------------------------------------------------------------------
# the live split under sustained load (the tentpole)
# ---------------------------------------------------------------------------

def test_live_split_under_load_zero_failed_lookups():
    old = _servers(2, native_read=True)
    new = _servers(4, version=1, importing=True, native_read=True)
    sc0, sc1 = _scheme(old, 0), _scheme(new, 1)
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([sv.table.copy() for sv in old])
    stop = threading.Event()
    failed_lookups = []
    reads = [0]

    def _reader():
        # a second client hammering reads throughout the split
        r = RemoteEmbedding([sc0, sc1], VOCAB, DIM, timeout_ms=10000,
                            retry=_retry_policy())
        try:
            while not stop.is_set():
                try:
                    r.lookup(ids[:64])
                    reads[0] += 1
                except Exception as e:  # noqa: BLE001 — the verdict
                    failed_lookups.append(repr(e))
                    return
        finally:
            r.close()

    t = threading.Thread(target=_reader, daemon=True)
    t.start()
    drv = MigrationDriver(sc0, sc1, VOCAB)
    acked = 0
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        acked += 1
        emb.push_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        emb.flush_gradients()
        acked += 1
        drv.start()
        drv.wait_caught_up(deadline_s=20)
        # writes DURING the copy phase flow through to the destinations
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        acked += 1
        # an UNFLUSHED push window rides across the cutover
        emb.push_gradients(ids, np.full((VOCAB, DIM), 0.25, np.float32))
        drv.cutover()
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        emb.flush_gradients()     # transfers the window, exactly once
        acked += 1
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        acked += 1
        stop.set()
        t.join(timeout=10)
        assert not failed_lookups, failed_lookups
        assert reads[0] > 0
        # exact ledger: every acked update exactly once (0.5/0.25/...
        # are dyadic — float32 subtraction is exact here)
        expect = before.copy()
        for d in (0.5, 0.5, 0.25, 0.25, 0.125):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in new]), expect)
        assert np.array_equal(emb.lookup(ids), expect)
        assert emb._wv.version == 1
        # retirement: the old scheme drains, its views drop, its native
        # tables release (handle ledger back to baseline)
        assert drv.wait_drained(idle_s=0.3, deadline_s=20)
        drv.retire()
        emb.set_schemes([sc0.with_(state="retired", weight=0.0)])
        assert [v.version for v in emb._views] == [1]
        shards_before_close = rpc.debug_handle_count("ps_shard")
        _close_all(old)
        old = []
        assert rpc.debug_handle_count("ps_shard") == \
            shards_before_close - 2
        assert np.array_equal(emb.lookup(ids), expect)
    finally:
        stop.set()
        drv.close()
        emb.close()
        _close_all(old, new)


# ---------------------------------------------------------------------------
# satellite: idempotent unary writes (request-id dedup window)
# ---------------------------------------------------------------------------

def test_unary_apply_dedup_window_exact():
    """A timed-out-but-APPLIED ApplyGradId attempt that retries is
    dropped server-side: two sends of the same (writer, seq) land
    EXACTLY one apply — proven with exact float arithmetic."""
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
    before = sv.table.copy()
    ids = np.arange(8, dtype=np.int32)
    grads = np.full((8, DIM), 0.5, np.float32)
    req = bytes(_pack_apply_id_req("w1/u0.0", 1, (), ids, grads))
    ch = rpc.Channel(sv.address, timeout_ms=5000)
    try:
        drops0 = int(obs.counter("ps_unary_dedup_drops").get_value())
        gen1 = struct.unpack("<q", ch.call("Ps", "ApplyGradId", req))[0]
        # the "retry" of an already-applied attempt: same writer+seq
        gen2 = struct.unpack("<q", ch.call("Ps", "ApplyGradId", req))[0]
        assert gen2 >= gen1 >= 1
        assert int(obs.counter("ps_unary_dedup_drops").get_value()) \
            == drops0 + 1
        expect = before.copy()
        expect[ids] -= np.float32(0.5)        # exactly ONE apply
        assert np.array_equal(sv.table, expect)
        # a later seq applies normally
        req2 = bytes(_pack_apply_id_req("w1/u0.0", 2, (), ids, grads))
        ch.call("Ps", "ApplyGradId", req2)
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(sv.table, expect)
        # a GUARD naming a covered frame drops the delta (the re-split
        # path: content already migrated here with the old rows)
        g0 = int(obs.counter("ps_scheme_guard_drops").get_value())
        req3 = bytes(_pack_apply_id_req("w2/u1.0", 1,
                                        (("w1/u0.0", 2),), ids, grads))
        ch.call("Ps", "ApplyGradId", req3)
        assert int(obs.counter("ps_scheme_guard_drops").get_value()) \
            == g0 + 1
        assert np.array_equal(sv.table, expect)   # unchanged
        # an UNcovered guard applies
        req4 = bytes(_pack_apply_id_req("w2/u1.0", 2,
                                        (("w9/u9.9", 5),), ids, grads))
        ch.call("Ps", "ApplyGradId", req4)
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(sv.table, expect)
    finally:
        ch.close()
        sv.close()


def test_unary_retry_through_embedding_is_exactly_once():
    """Through RemoteEmbedding: the first attempt errors client-side
    AFTER... actually BEFORE the wire — the retry carries the SAME
    (writer, seq), so whichever attempts reach the server, the table
    moves exactly once per batch."""
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
    before = sv.table.copy()
    emb = RemoteEmbedding([sv.address], VOCAB, DIM, timeout_ms=5000,
                          retry=_retry_policy())
    ids = np.arange(16, dtype=np.int32)
    try:
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="error", side="client", service="Ps",
            method="ApplyGradId", error_code=1009, max_hits=1)],
            seed=3))
        for _ in range(3):
            emb.apply_gradients(ids, np.full((16, DIM), 0.25,
                                             np.float32))
        expect = before.copy()
        for _ in range(3):
            expect[ids] -= np.float32(0.25)
        assert np.array_equal(sv.table, expect)
    finally:
        fault.clear()
        emb.close()
        sv.close()


# ---------------------------------------------------------------------------
# migration under fault
# ---------------------------------------------------------------------------

def test_migration_stream_severed_midcopy_recovers_byte_identical():
    """Sever the handoff plane of one destination mid-copy: the shipper
    backs off, reconnects, RESYNCS the range wholesale, and the split
    completes byte-identical — the 'kill the migration source's stream'
    scenario with full recovery."""
    old = _servers(2)
    new = _servers(4, version=1, importing=True)
    sc0, sc1 = _scheme(old, 0), _scheme(new, 1)
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([sv.table.copy() for sv in old])
    drv = MigrationDriver(sc0, sc1, VOCAB)
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        # the first 3 handoff attempts at destination 1 die mid-stream
        fault.install(fault.FaultPlan(fault.partition_rules(
            new[1].address, max_hits=3), seed=5))
        drv.start()
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        drv.wait_caught_up(deadline_s=20)
        fault.clear()
        drv.cutover()
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25, 0.125):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in new]), expect)
        assert int(obs.counter(
            "ps_migrate_connect_errors").get_value()) >= 1
    finally:
        fault.clear()
        drv.close()
        emb.close()
        _close_all(old, new)


def test_dead_destination_aborts_cleanly():
    """A destination dead before cutover: catch-up times out loudly,
    abort() stops the shippers, and the old scheme keeps serving with
    every acked update intact — nothing was fenced, nothing lost."""
    old = _servers(2)
    new = _servers(4, version=1, importing=True)
    sc0, sc1 = _scheme(old, 0), _scheme(new, 1)
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([sv.table.copy() for sv in old])
    drv = MigrationDriver(sc0, sc1, VOCAB, timeout_ms=1000)
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        fault.install(fault.FaultPlan(
            fault.kill_rules(new[2].address), seed=7))
        drv.start()
        with pytest.raises(rpc.RpcError):
            drv.wait_caught_up(deadline_s=1.5)
        drv.abort()
        # the old scheme was never touched: writes and reads flow
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in old]), expect)
        assert np.array_equal(emb.lookup(ids), expect)
        st = drv.migrate_state(0)
        assert not st["active"]
    finally:
        fault.clear()
        drv.close()
        emb.close()
        _close_all(old, new)


def test_stale_writer_racing_cutover_registry_refresh():
    """A writer that KEEPS writing through the cutover with only the
    old scheme: the fence answers ESCHEMEMOVED, the client refreshes
    from the registry (watcher), re-splits the batch with guards, and
    the final tables hold EXACTLY one application per acked batch."""
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    reg_port = reg_server.start("127.0.0.1:0")
    reg_addr = f"127.0.0.1:{reg_port}"
    old = _servers(2)
    new = _servers(4, version=1, importing=True)
    sc0, sc1 = _scheme(old, 0), _scheme(new, 1)
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", sc0)
    emb = RemoteEmbedding.from_registry(
        reg_addr, "ps", VOCAB, DIM, timeout_ms=10000, watch=True,
        retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = np.concatenate([sv.table.copy() for sv in old])
    delta = np.full((VOCAB, DIM), 0.5, np.float32)
    stop = threading.Event()
    acked = [0]
    errors = []

    def _writer():
        while not stop.is_set():
            try:
                emb.apply_gradients(ids, delta)
                acked[0] += 1
            except Exception as e:  # noqa: BLE001 — the verdict
                errors.append(repr(e))
                return

    drv = MigrationDriver(sc0, sc1, VOCAB, registry_addr=reg_addr,
                          cluster="ps")
    t = threading.Thread(target=_writer, daemon=True)
    t.start()
    try:
        time.sleep(0.1)               # some pre-split batches land
        drv.run(deadline_s=30)        # copy → catch-up → fenced cutover
        time.sleep(0.3)               # post-split batches land
        stop.set()
        t.join(timeout=10)
        assert not errors, errors
        assert acked[0] > 2
        # flush whatever the writer left in combiners, then the ledger
        for sv in new:
            ch = rpc.Channel(sv.address, timeout_ms=2000)
            try:
                ch.call("Ps", "Flush", b"")
            finally:
                ch.close()
        expect = before.copy()
        for _ in range(acked[0]):
            expect[ids] -= np.float32(0.5)
        assert np.array_equal(
            np.concatenate([sv.table for sv in new]), expect)
        assert emb._wv.version == 1
        assert int(obs.counter("ps_scheme_moved_writes").get_value()) \
            >= 0   # fence may or may not race a batch; exactness above
    finally:
        stop.set()
        drv.close()
        emb.close()
        nc.close()
        reg_server.close()
        _close_all(old, new)


# ---------------------------------------------------------------------------
# satellite: registry claims drive failover
# ---------------------------------------------------------------------------

def test_failover_adopts_registry_claim_without_sweeping():
    servers = [PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
               for _ in range(2)]
    prim, backup = servers
    rs = ReplicaSet((prim.address, backup.address), primary=0)
    prim.configure_replication(rs, 0)
    backup.configure_replication(rs, 1)
    emb = RemoteEmbedding([rs], VOCAB, DIM, timeout_ms=5000,
                          retry=_retry_policy())
    ids = np.arange(8, dtype=np.int32)
    grads = np.ones((8, DIM), np.float32)
    try:
        emb.apply_gradients(ids, grads)
        # let the backup's first Sync land (propagation is eventual
        # until the delta stream establishes) so the claimed primary
        # is not gen-behind the acked floor
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not np.array_equal(
                prim.table, backup.table):
            time.sleep(0.01)
        assert np.array_equal(prim.table, backup.table)
        # out-of-band promotion; the backup's heartbeat would publish
        # the claim — simulate the watcher ingesting it
        ch = rpc.Channel(backup.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch.close()
        assert parse_claim_tag(backup.claim_tag()) \
            == (0, 1, 1, 1, True, 0)
        emb._ingest_nodes([{"addr": backup.address,
                            "tag": backup.claim_tag()}])
        # primary dies; the next write must adopt the CLAIMED primary
        # directly (one ReplicaState verify, no sweep, no promote)
        fault.install(fault.FaultPlan(
            fault.kill_rules(prim.address), seed=11))
        adoptions0 = int(obs.counter("ps_claim_adoptions").get_value())
        promotes0 = int(obs.counter("ps_client_promotes").get_value())
        emb.apply_gradients(ids, grads)
        assert int(obs.counter("ps_claim_adoptions").get_value()) \
            == adoptions0 + 1
        assert int(obs.counter("ps_client_promotes").get_value()) \
            == promotes0
        assert emb._primary_idx[0] == 1
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# heartbeat tag_fn (the publishing half of the claims satellite)
# ---------------------------------------------------------------------------

def test_heartbeat_republishes_claim_tag():
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    port = reg_server.start("127.0.0.1:0")
    sv = PsShardServer(VOCAB, DIM, 0, 1)
    nc = NamingClient(f"127.0.0.1:{port}")
    try:
        nc.register("ps", sv.address, ttl_ms=300, tag_fn=sv.claim_tag)
        nodes, _ = nc.list("ps")
        assert parse_claims(nodes)[(0, 1, 0)] == (0, sv.address)
        # state changes; the next heartbeat re-publishes the new claim
        with sv._repl_mu:
            sv._epoch = 3
        deadline = time.monotonic() + 5.0
        claim = None
        while time.monotonic() < deadline:
            nodes, _ = nc.list("ps")
            claim = parse_claims(nodes).get((0, 1, 0))
            if claim == (3, sv.address):
                break
            time.sleep(0.05)
        assert claim == (3, sv.address)
    finally:
        nc.close()
        sv.close()
        reg_server.close()


# ---------------------------------------------------------------------------
# review regressions: failure paths of the transfer/fence machinery
# ---------------------------------------------------------------------------

def test_push_window_survives_failed_transfer_then_drains():
    """A fence with NO known successor must fail the push/flush loudly
    while keeping the unacked window intact — a later flush (once the
    successor is published) drains it exactly once.  Regression: the
    window used to be cleared before the successor lookup, so the
    frames were silently dropped and the next flush vacuously
    succeeded."""
    old = _servers(1)
    new = _servers(1, version=1)       # live successor, not yet known
    emb = RemoteEmbedding([_scheme(old, 0)], VOCAB, DIM,
                          timeout_ms=5000, retry=_retry_policy())
    ids = np.arange(8, dtype=np.int32)
    grads = np.ones((8, DIM), np.float32)
    try:
        ch = rpc.Channel(old[0].address, timeout_ms=5000)
        try:
            ch.call("Ps", "SchemeFence", struct.pack("<q", 1))
        finally:
            ch.close()
        before_new = new[0].table.copy()
        with pytest.raises(rpc.RpcError):
            emb.push_gradients(ids, grads)   # redirect, nowhere to go
        assert any(emb._push_unacked.values())
        with pytest.raises(rpc.RpcError):
            emb.flush_gradients()            # still loud, never vacuous
        assert any(emb._push_unacked.values()) or emb._push_carry
        emb.set_schemes([_scheme(new, 1)])   # successor published
        emb.flush_gradients()
        assert not any(emb._push_unacked.values())
        assert not emb._push_carry
        expect = before_new.copy()
        expect[ids] -= np.float32(1.0)
        assert np.array_equal(new[0].table, expect)
        emb.flush_gradients()                # nothing left to re-apply
        assert np.array_equal(new[0].table, expect)
    finally:
        emb.close()
        _close_all(old, new)


def test_fence_rolls_back_when_final_flush_fails():
    """SchemeFence whose migration flush cannot settle (dead
    destination) must not leave the source stuck fenced: the flag rolls
    back, writes are readmitted, and the driver can retry or abort."""
    old = _servers(1)
    old[0].repl_ack_timeout_s = 0.5
    emb = RemoteEmbedding([_scheme(old, 0)], VOCAB, DIM,
                          timeout_ms=5000, retry=_retry_policy())
    ids = np.arange(8, dtype=np.int32)
    ch = rpc.Channel(old[0].address, timeout_ms=5000)
    try:
        spec = json.dumps({"scheme": 1, "targets": [
            {"addr": "127.0.0.1:9", "base": 0, "rows": VOCAB}]})
        ch.call("Ps", "MigrateStart", spec.encode())
        with pytest.raises(rpc.RpcError):
            ch.call("Ps", "SchemeFence", struct.pack("<q", 1))
        info = json.loads(ch.call("Ps", "SchemeInfo", b""))
        assert not info["fenced"]
        assert info["next_scheme"] is None
        ch.call("Ps", "MigrateStop", b"")
        before = old[0].table.copy()
        emb.apply_gradients(ids, np.ones((8, DIM), np.float32))
        expect = before.copy()
        expect[ids] -= np.float32(1.0)
        assert np.array_equal(old[0].table, expect)
    finally:
        ch.close()
        emb.close()
        _close_all(old)


def test_abort_unfences_every_source():
    """A cutover that fenced a source and then died strands writers
    unless abort() rolls the fence back: MigrateStop alone used to
    leave the source answering ESCHEMEMOVED forever with no successor
    ever published."""
    old = _servers(1)
    sc0 = _scheme(old, 0)
    sc1 = PartitionScheme(1, (ReplicaSet.of("127.0.0.1:9"),))
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=5000,
                          retry=_retry_policy())
    ids = np.arange(8, dtype=np.int32)
    drv = MigrationDriver(sc0, sc1, VOCAB, timeout_ms=2000)
    try:
        ch = rpc.Channel(old[0].address, timeout_ms=5000)
        try:
            ch.call("Ps", "SchemeFence", struct.pack("<q", 1))
        finally:
            ch.close()
        with pytest.raises(rpc.RpcError):
            emb.apply_gradients(ids, np.ones((8, DIM), np.float32))
        before = old[0].table.copy()
        drv.abort()                      # MigrateStop + SchemeUnfence
        emb.apply_gradients(ids, np.full((8, DIM), 0.5, np.float32))
        expect = before.copy()
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(old[0].table, expect)
    finally:
        drv.close()
        emb.close()
        _close_all(old)


def test_ingest_skips_unroutable_scheme_records():
    """A published scheme this client cannot build a view for (shard
    count not dividing its vocab) must not kill ingestion — the watcher
    keeps consuming the records it CAN use.  Direct set_schemes stays
    strict."""
    from brpc_tpu.naming import SCHEME_TAG_PREFIX, scheme_record_addr
    old = _servers(1)
    emb = RemoteEmbedding([_scheme(old, 0)], VOCAB, DIM,
                          timeout_ms=5000, retry=_retry_policy())
    bad = PartitionScheme(3, tuple(
        ReplicaSet.of(f"127.0.0.1:{p}") for p in (11, 12, 13)))
    assert VOCAB % 3                     # genuinely unroutable
    rejects0 = int(obs.counter("ps_scheme_rejects").get_value())
    try:
        emb._ingest_nodes([
            {"addr": scheme_record_addr(3),
             "tag": SCHEME_TAG_PREFIX + bad.to_json()},
            {"addr": old[0].address,
             "tag": shard_tag(0, 1, epoch=5, primary=True, scheme=0)},
        ])                               # must not raise
        assert int(obs.counter("ps_scheme_rejects").get_value()) \
            == rejects0 + 1
        # the claim in the same listing still landed
        assert emb._claims[(0, 1, 0)] == (5, old[0].address)
        assert [v.version for v in emb._views] == [0]
        with pytest.raises(ValueError):
            emb.set_schemes([bad])       # the public API stays strict
    finally:
        emb.close()
        _close_all(old)


def test_claims_scoped_per_scheme_version():
    """Two coexisting schemes with the SAME shard count must not mask
    each other's primary claims; a view prefers its own scheme's claim
    and falls back to a legacy unscoped one only when no scoped claim
    exists."""
    claims = parse_claims([
        {"addr": "a:1", "tag": "0/2@v0e4P"},
        {"addr": "b:1", "tag": "0/2@v1e9P"},
        {"addr": "c:1", "tag": "0/2@e2P"},
    ])
    assert claims[(0, 2, 0)] == (4, "a:1")
    assert claims[(1, 2, 0)] == (9, "b:1")
    assert claims[(None, 2, 0)] == (2, "c:1")
    old = _servers(2)
    emb = RemoteEmbedding([_scheme(old, 0)], VOCAB, DIM,
                          timeout_ms=5000)
    try:
        with emb._view_mu:
            emb._claims.update(claims)
        view = emb._wv
        # v1's higher epoch no longer masks this view's own claim
        assert emb._claim_for(view, 0) == (4, "a:1")
        with emb._view_mu:
            del emb._claims[(0, 2, 0)]
        assert emb._claim_for(view, 0) == (2, "c:1")   # legacy fallback
    finally:
        emb.close()
        _close_all(old)


def test_shipper_flush_raises_when_stopped_early():
    """A stop/abort racing the cutover flush must fail it loudly — a
    fence that 'succeeds' without every destination holding the final
    generation is exactly the hole the barrier exists to close."""
    from brpc_tpu.reshard import MigrationShipper
    sh = MigrationShipper(None, [{"addr": "x:1", "base": 0, "rows": 8}],
                          scheme=1)
    sh._stop.set()
    with pytest.raises(rpc.RpcError) as ei:
        sh.flush(3, timeout_s=1.0)
    assert "stopped" in str(ei.value)


# ---------------------------------------------------------------------------
# fault-tolerant migration (ISSUE 13): re-drive, replicated successors,
# gradual weights
# ---------------------------------------------------------------------------

def _replicated_servers(num, nrep, version=0, importing=False, **kw):
    """num shards x nrep replicas, replication configured (auto quorum:
    majority for nrep>=3).  Returns (servers[s][r], replica_sets)."""
    servers = [[PsShardServer(VOCAB, DIM, s, num, lr=1.0, stream=True,
                              importing=importing,
                              scheme_version=version, **kw)
                for _ in range(nrep)] for s in range(num)]
    sets = []
    for s in range(num):
        rs = ReplicaSet(tuple(sv.address for sv in servers[s]),
                        primary=0)
        sets.append(rs)
        for r, sv in enumerate(servers[s]):
            sv.configure_replication(rs, r)
    return servers, sets


def test_source_primary_death_mid_migration_redrives():
    """Kill the source primary MID-COPY: the promoted backup re-drives
    the migration from its replicated spec (no manual MigrateStart),
    the driver's live-primary resolution follows it, the cutover
    completes, and the exactly-once ApplyGradId windows hold across
    the re-drive — the exact ledger is the proof."""
    src, src_sets = _replicated_servers(1, 3)
    dst = _servers(2, version=1, importing=True)
    sc0 = PartitionScheme(0, tuple(src_sets))
    sc1 = _scheme(dst, 1)
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = src[0][0].table.copy()
    drv = MigrationDriver(sc0, sc1, VOCAB, timeout_ms=3000)
    redrives0 = int(obs.counter("ps_migration_redrives").get_value())
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                         np.float32))
        drv.start()
        # the source primary dies mid-copy (streams severed too)
        fault.install(fault.FaultPlan(
            fault.kill_rules(src[0][0].address), seed=23))
        rpc.debug_fail_connections(src[0][0].address)
        # a write triggers client failover -> Promote -> auto re-drive;
        # its seq window must survive the re-drive exactly-once
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        assert int(obs.counter("ps_migration_redrives").get_value()) \
            == redrives0 + 1
        drv.wait_caught_up(deadline_s=30)
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        drv.cutover()
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.0625,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25, 0.125, 0.0625):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in dst]), expect)
        assert np.array_equal(emb.lookup(ids), expect)
    finally:
        fault.clear()
        drv.close()
        emb.close()
        _close_all(dst)
        _close_all(*src)


def test_replicated_successor_backups_hold_import():
    """MigrateSync/MigrateApply propagate to DESTINATION backups: after
    the cutover every destination backup is byte-identical to its
    primary, and killing a destination primary right after cutover
    loses nothing — the promoted backup already holds every migrated
    row (majority sweep over 3 replicas)."""
    old = _servers(1)
    dst, dst_sets = _replicated_servers(2, 3, version=1,
                                        importing=True)
    sc0 = _scheme(old, 0)
    sc1 = PartitionScheme(1, tuple(dst_sets))
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    before = old[0].table.copy()
    drv = MigrationDriver(sc0, sc1, VOCAB, timeout_ms=3000)
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                         np.float32))
        drv.start()
        drv.wait_caught_up(deadline_s=30)
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        drv.cutover()
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        expect = before.copy()
        for d in (0.5, 0.25):
            expect[ids] -= np.float32(d)
        # every destination replica holds the migrated rows
        deadline = time.monotonic() + 5.0
        def _replicas_identical():
            return all(np.array_equal(sv.table, dst[s][0].table)
                       for s in range(2) for sv in dst[s][1:])
        while time.monotonic() < deadline and not _replicas_identical():
            time.sleep(0.02)
        assert _replicas_identical()
        assert np.array_equal(
            np.concatenate([dst[s][0].table for s in range(2)]),
            expect)
        # kill destination shard 0's primary: the write fails over to
        # a backup that already holds the import
        fault.install(fault.FaultPlan(
            fault.kill_rules(dst[0][0].address), seed=29))
        rpc.debug_fail_connections(dst[0][0].address)
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        expect2 = expect.copy()
        expect2[ids] -= np.float32(0.125)
        got = np.concatenate([
            next(sv for sv in dst[0] if sv.is_primary
                 and sv is not dst[0][0]).table,
            next(sv for sv in dst[1] if sv.is_primary).table])
        assert np.array_equal(got, expect2)
    finally:
        fault.clear()
        drv.close()
        emb.close()
        _close_all(old)
        _close_all(*dst)


def test_weight_ramp_publishes_gradual_shift():
    """ramp_weights replaces the binary 1->0 read cutover: each step
    publishes successor ACTIVE at w and the retiring scheme ACTIVE at
    1-w; the final step lands exactly the binary end state (successor
    active 1.0, old draining 0).  Writes already belong to the
    successor at every step (newest active)."""
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    reg_addr = f"127.0.0.1:{reg_server.start('127.0.0.1:0')}"
    old = _servers(1)
    new = _servers(2, version=1, importing=True)
    sc0, sc1 = _scheme(old, 0), _scheme(new, 1)
    nc = NamingClient(reg_addr)
    publish_scheme(nc, "ps", sc0)
    drv = MigrationDriver(sc0, sc1, VOCAB, registry_addr=reg_addr,
                          cluster="ps", timeout_ms=3000)
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(VOCAB, dtype=np.int32)
    mid_states = []
    try:
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5,
                                         np.float32))
        drv.start()
        drv.wait_caught_up(deadline_s=20)
        drv.cutover()   # publishes the binary transition...
        # ...then the ramp re-publishes the gradual shift
        drv.ramp_weights(steps=(0.5, 1.0), interval_s=0.05)
        nodes, _ = nc.list("ps")
        schemes = parse_schemes(nodes)
        assert schemes[1].state == "active"
        assert schemes[1].weight == 1.0
        assert schemes[0].state == "draining"
        assert schemes[0].weight == 0.0
        # a mid-ramp publication really happened: run a ramp with a
        # long interval and observe the registry between its steps
        drv2 = MigrationDriver(sc0, sc1, VOCAB,
                               registry_addr=reg_addr, cluster="ps",
                               timeout_ms=3000)
        t = threading.Thread(
            target=lambda: drv2.ramp_weights(steps=(0.25, 1.0),
                                             interval_s=0.6),
            daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            nodes, _ = nc.list("ps")
            schemes = parse_schemes(nodes)
            state = (schemes[1].weight, schemes[0].weight,
                     schemes[0].state)
            if state == (0.25, 0.75, "active"):
                mid_states.append(state)
                break
            time.sleep(0.02)
        t.join(timeout=10)
        drv2.close()
        # the sub-1.0 step kept BOTH schemes active with complementary
        # weights — the gradual read shift; the final step completed
        assert mid_states == [(0.25, 0.75, "active")]
        nodes, _ = nc.list("ps")
        schemes = parse_schemes(nodes)
        assert (schemes[1].weight, schemes[0].state) == (1.0,
                                                         "draining")
    finally:
        drv.close()
        emb.close()
        nc.close()
        reg_server.close()
        _close_all(old, new)


def test_scheme_watcher_ingests_hostile_claims_keeps_valid():
    """_SchemeWatcher ingest: malformed claim nodes (no addr, non-str
    tags, negative epochs), DUPLICATE claims (highest epoch must win),
    and junk scheme records must neither raise nor shadow the valid
    records in the same listing."""
    old = _servers(1)
    emb = RemoteEmbedding([_scheme(old, 0)], VOCAB, DIM,
                          timeout_ms=5000)
    from brpc_tpu.naming import SCHEME_TAG_PREFIX
    good = _scheme(old, 0).with_(weight=0.5)
    try:
        emb._ingest_nodes([
            {"tag": "0/1@e7P"},                      # claim, no addr
            {"addr": 9, "tag": "0/1@e8P"},           # non-str addr
            {"addr": "x:1", "tag": ["0/1@e9P"]},     # non-str tag
            {"addr": "x:1", "tag": "0/1@e-3P"},      # negative epoch
            {"addr": "a:1", "tag": "0/1@e2P"},       # valid, low epoch
            {"addr": "b:1", "tag": "0/1@e5P"},       # valid duplicate
            {"addr": "c:1", "tag": "0/1@e4P"},       # lower: ignored
            {"addr": "0.0.0.0:0",
             "tag": SCHEME_TAG_PREFIX + "{not json"},
            {"addr": "0.0.0.0:0",
             "tag": SCHEME_TAG_PREFIX + good.to_json()},
        ])
        # highest-epoch duplicate won; nothing raised; the valid
        # re-published scheme updated the view's weight
        assert emb._claims[(None, 1, 0)] == (5, "b:1")
        assert emb._wv.weight == 0.5
    finally:
        emb.close()
        _close_all(old)
