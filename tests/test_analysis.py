"""Unit tests for the framework-invariant linter (brpc_tpu.analysis.lint):
each check must fire on a seeded violation and stay quiet on the fixed
form of the same code."""

import json
import os
import subprocess
import sys
import textwrap

from brpc_tpu.analysis import lint


def _lint_src(tmp_path, src, name="mod.py", checks=None):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return lint.lint_files([str(p)], checks)


def _by_check(findings, check):
    return [f for f in findings if f.check == check]


# ---- ctypes-contract: argtypes/restype ----

def test_undeclared_brt_symbol_flagged(tmp_path):
    fs = _lint_src(tmp_path, "lib.brt_mystery(1)\n")
    (f,) = _by_check(fs, "ctypes-contract")
    assert "brt_mystery" in f.message
    assert "argtypes and restype" in f.message
    assert f.line == 1


def test_partial_declaration_flags_missing_restype(tmp_path):
    fs = _lint_src(tmp_path, """\
        lib.brt_thing.argtypes = []
        lib.brt_thing(1)
    """)
    (f,) = _by_check(fs, "ctypes-contract")
    assert "restype" in f.message and "argtypes and" not in f.message


def test_fully_declared_symbol_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        lib.brt_ok.argtypes = [ctypes.c_int]
        lib.brt_ok.restype = ctypes.c_void_p
        lib.brt_ok(1)
    """)
    assert fs == []


def test_declaration_in_sibling_file_counts(tmp_path):
    (tmp_path / "decls.py").write_text(
        "lib.brt_shared.argtypes = []\nlib.brt_shared.restype = None\n")
    (tmp_path / "use.py").write_text("x._lib.brt_shared()\n")
    assert lint.run_lint([str(tmp_path)]) == []


# ---- ctypes-contract: CFUNCTYPE pinning ----

def test_inline_cfunctype_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        def register(lib, cb):
            lib.brt_reg(_H(cb))
    """)
    (f,) = _by_check(fs, "ctypes-contract")
    assert "inline" in f.message and "GC" in f.message


def test_unpinned_callback_flagged_and_pinned_clean(tmp_path):
    bad = """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        class S:
            def add(self, lib):
                @_H
                def tramp():
                    pass
                lib.brt_reg(tramp)
    """
    fs = _lint_src(tmp_path, bad, name="bad.py")
    (f,) = _by_check(fs, "ctypes-contract")
    assert "tramp" in f.message and "pinned" in f.message

    good = bad.replace("lib.brt_reg(tramp)",
                       "lib.brt_reg(tramp)\n"
                       "                self._handlers.append(tramp)")
    assert _lint_src(tmp_path, good, name="good.py") == []


def test_attribute_pinning_counts(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        class S:
            def add(self, lib):
                cb = _H(lambda: None)
                self._cb = cb
                lib.brt_reg(cb)
    """)
    assert fs == []


# ---- fiber-shared-state ----

_HANDLER_CLASS = """\
    import threading

    class Shard:
        def __init__(self, server):
            self._mu = threading.Lock()
            self.count = 0
            server.add_service("Ps", self._handle)

        def _handle(self, method, req):
            {body}
            return b""
"""


def test_unlocked_handler_mutation_flagged(tmp_path):
    fs = _lint_src(tmp_path,
                   _HANDLER_CLASS.format(body="self.count += 1"))
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "Shard._handle" in f.message and "self.count" in f.message


def test_locked_handler_mutation_clean(tmp_path):
    fs = _lint_src(tmp_path, _HANDLER_CLASS.format(
        body="with self._mu:\n                self.count += 1"))
    assert _by_check(fs, "fiber-shared-state") == []


def test_ufunc_at_mutation_flagged(tmp_path):
    fs = _lint_src(tmp_path, _HANDLER_CLASS.format(
        body="np.subtract.at(self.table, req, 1)"))
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "self.table" in f.message


def test_mutation_via_helper_method_flagged(tmp_path):
    src = """\
        class Shard:
            def __init__(self, server):
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                self._serve(req)
                return b""

            def _serve(self, req):
                self.rows.append(req)
    """
    fs = _lint_src(tmp_path, src)
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "Shard._serve" in f.message


def test_helper_only_called_under_lock_clean(tmp_path):
    src = """\
        import threading

        class Shard:
            def __init__(self, server):
                self._mu = threading.Lock()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                with self._mu:
                    self._serve(req)
                return b""

            def _serve(self, req):
                self.rows = req
    """
    assert _lint_src(tmp_path, src) == []


def test_non_handler_class_not_audited(tmp_path):
    src = """\
        class Plain:
            def poke(self):
                self.count = 1
    """
    assert _lint_src(tmp_path, src) == []


# ---- fiber-shared-state: rwlock read()/write() contexts ----

_RW_HANDLER = """\
    from brpc_tpu.analysis.race import checked_rwlock

    class Shard:
        def __init__(self, server):
            self._mu = checked_rwlock("t.shard")
            self.count = 0
            server.add_service("Ps", self._handle)

        def _handle(self, method, req):
            {body}
            return b""
"""


def test_mutation_under_write_side_clean(tmp_path):
    fs = _lint_src(tmp_path, _RW_HANDLER.format(
        body="with self._mu.write():\n                self.count += 1"))
    assert _by_check(fs, "fiber-shared-state") == []


def test_mutation_under_read_side_flagged(tmp_path):
    """The read side is SHARED — it must never legitimize mutation."""
    fs = _lint_src(tmp_path, _RW_HANDLER.format(
        body="with self._mu.read():\n                self.count += 1"))
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "self.count" in f.message
    assert "read-side" in f.message and "write" in f.message


def test_read_only_access_under_read_side_clean(tmp_path):
    fs = _lint_src(tmp_path, _RW_HANDLER.format(
        body="with self._mu.read():\n                x = self.count"))
    assert _by_check(fs, "fiber-shared-state") == []


def test_read_side_does_not_propagate_as_lock_through_calls(tmp_path):
    src = """\
        from brpc_tpu.analysis.race import checked_rwlock

        class Shard:
            def __init__(self, server):
                self._mu = checked_rwlock("t.shard")
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                with self._mu.read():
                    self._bump()
                return b""

            def _bump(self):
                self.count = 1
    """
    fs = _lint_src(tmp_path, src)
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "Shard._bump" in f.message


# ---- obs-guard ----

def test_direct_registry_use_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu import obs

        def hot(n):
            obs.counter("x").add(n)      # allowed: no-op-able helper
            a = obs.Adder()              # direct reducer construction
            obs.default_registry()       # direct registry access
            obs.expose("y", a)           # direct expose
    """)
    fs = _by_check(fs, "obs-guard")
    assert len(fs) == 3
    assert all("no-op-able" in f.message for f in fs)


def test_obs_package_itself_exempt(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu import obs
        obs.Adder()
    """, name=os.path.join("obs", "inner.py"))
    assert _by_check(fs, "obs-guard") == []


# ---- trace-purity ----

def test_impure_jit_function_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time
        import jax
        from functools import partial
        from brpc_tpu import obs

        @jax.jit
        def step(x):
            print(x)
            t = time.time()
            return x + t

        @partial(jax.jit, static_argnames=())
        def counted(x):
            obs.counter("steps").add(1)
            return x

        traced = jax.jit(lambda x: print(x))
    """)
    fs = _by_check(fs, "trace-purity")
    assert len(fs) == 4
    kinds = " | ".join(f.message for f in fs)
    assert "print" in kinds and "time.time" in kinds and "obs" in kinds


def test_shard_map_lock_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        from functools import partial
        from brpc_tpu._compat import shard_map

        class C:
            def op(self, x):
                @partial(shard_map, mesh=self.mesh, in_specs=None,
                         out_specs=None)
                def _f(shard):
                    with self._mu:
                        return shard
                return _f(x)
    """)
    (f,) = _by_check(fs, "trace-purity")
    assert "lock" in f.message


def test_pure_jit_function_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x * 2)
    """)
    assert fs == []


# ---- trace-purity: host callbacks under trace ----

def test_host_callback_flagged_and_pragma_allowlists(tmp_path):
    fs = _lint_src(tmp_path, """\
        import jax

        @jax.jit
        def noisy(x):
            jax.debug.print("x={}", x)
            return x

        @jax.jit
        def wanted(x):
            jax.debug.print("x={}", x)  # lint: allow-host-callback
            return jax.pure_callback(lambda v: v, x, x)
    """)
    fs = _by_check(fs, "trace-purity")
    assert len(fs) == 2
    msgs = " | ".join(f.message for f in fs)
    assert "jax.debug.print" in msgs and "pure_callback" in msgs
    assert all("host round-trip" in f.message for f in fs)
    # the allowlisted debug.print on its own line did NOT fire
    assert not any(f.line == 10 for f in fs)


def test_host_callback_transitive_chain(tmp_path):
    fs = _lint_src(tmp_path, """\
        import jax

        def helper(x):
            return jax.experimental.io_callback(lambda v: v, x, x)

        @jax.jit
        def step(x):
            return helper(x)
    """)
    (f,) = _by_check(fs, "trace-purity")
    assert "io_callback" in f.message
    assert "step -> helper" in f.message


# ---- lock-order (static inversion cycles) ----

_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock

    lock_a = checked_lock("fix.A")
    lock_b = checked_lock("fix.B")

    def order_ab():
        with lock_a:
            take_b()

    def take_b():
        with lock_b:
            pass

    def order_ba():
        with lock_b:
            with lock_a:
                pass
"""


def test_static_lock_order_inversion(tmp_path):
    fs = _lint_src(tmp_path, _LOCK_FIXTURE)
    (f,) = _by_check(fs, "lock-order")
    assert "fix.A" in f.message and "fix.B" in f.message
    assert "deadlock" in f.message
    # both acquisition contexts are named, incl. the call chain
    assert "order_ab -> take_b" in f.message
    assert "order_ba" in f.message


def test_static_lock_order_consistent_nesting_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock

        lock_a = checked_lock("ok.A")
        lock_b = checked_lock("ok.B")

        def one():
            with lock_a:
                with lock_b:
                    pass

        def two():
            with lock_a:
                with lock_b:
                    pass
    """)
    assert _by_check(fs, "lock-order") == []


def test_static_lock_order_instance_locks(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock

        class S:
            def __init__(self):
                self._mu = checked_lock("inst.A")
                self._table_mu = checked_lock("inst.B")

            def fwd(self):
                with self._mu:
                    with self._table_mu:
                        pass

            def rev(self):
                with self._table_mu:
                    with self._mu:
                        pass
    """)
    (f,) = _by_check(fs, "lock-order")
    assert "inst.A" in f.message and "inst.B" in f.message


_RW_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock, checked_rwlock

    rw = checked_rwlock("rwfix.A")
    mu = checked_lock("rwfix.B")

    def read_then_lock():
        with rw.read():
            with mu:
                pass

    def lock_then_write():
        with mu:
            with rw.write():
                pass
"""


def test_static_lock_order_sees_rwlock_sides(tmp_path):
    """checked_rwlock's read()/write() contexts acquire under the lock's
    one name, so a read-vs-write inversion against another lock is a
    static cycle — parity with the dynamic harness's keying."""
    fs = _lint_src(tmp_path, _RW_LOCK_FIXTURE)
    (f,) = _by_check(fs, "lock-order")
    assert "rwfix.A" in f.message and "rwfix.B" in f.message
    assert "deadlock" in f.message


def test_static_lock_order_rwlock_consistent_order_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock, checked_rwlock

        rw = checked_rwlock("rwok.A")
        mu = checked_lock("rwok.B")

        def reader():
            with rw.read():
                with mu:
                    pass

        def writer():
            with rw.write():
                with mu:
                    pass
    """)
    assert _by_check(fs, "lock-order") == []


def test_static_rwlock_inversion_matches_dynamic_harness(tmp_path):
    from brpc_tpu.analysis import race

    static = _by_check(_lint_src(tmp_path, _RW_LOCK_FIXTURE), "lock-order")
    assert len(static) == 1

    race.clear()
    race.set_enabled(True)
    try:
        ns = {"checked_lock": race.checked_lock,
              "checked_rwlock": race.checked_rwlock}
        exec(textwrap.dedent(_RW_LOCK_FIXTURE).split("\n", 1)[1], ns)
        ns["read_then_lock"]()
        ns["lock_then_write"]()
        dynamic = [f for f in race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        race.set_enabled(None)
        race.clear()
    assert len(dynamic) == 1
    assert {"rwfix.A", "rwfix.B"} <= set(dynamic[0].locks)


def test_static_lock_order_matches_dynamic_harness(tmp_path):
    """The acceptance contract: the static pass reproduces the dynamic
    harness's inversion finding on the same fixture — RACECHECK becomes
    the confirmer, not the only detector."""
    from brpc_tpu.analysis import race

    static = _by_check(_lint_src(tmp_path, _LOCK_FIXTURE), "lock-order")
    assert len(static) == 1
    static_locks = {n for n in ("fix.A", "fix.B")
                    if n in static[0].message}

    race.clear()
    race.set_enabled(True)
    try:
        ns = {"checked_lock": race.checked_lock}
        exec(textwrap.dedent(_LOCK_FIXTURE).split("\n", 1)[1], ns)
        ns["order_ab"]()
        ns["order_ba"]()
        dynamic = [f for f in race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        race.set_enabled(None)
        race.clear()
    assert len(dynamic) == 1
    assert static_locks == {"fix.A", "fix.B"} <= set(dynamic[0].locks)


# ---- stable finding ids + baseline ----

def test_finding_id_stable_under_line_drift(tmp_path):
    (f1,) = _lint_src(tmp_path, "lib.brt_bad(1)\n", name="v1.py")
    (f2,) = _lint_src(tmp_path, "# a comment pushing the line\n"
                                "\nlib.brt_bad(1)\n", name="v1.py")
    assert f1.line != f2.line
    assert f1.id == f2.id  # id hashes check+path+message, not the line


def test_finding_id_differs_across_checks_and_files(tmp_path):
    (a,) = _lint_src(tmp_path, "lib.brt_one(1)\n", name="a.py")
    (b,) = _lint_src(tmp_path, "lib.brt_one(1)\n", name="b.py")
    assert a.id != b.id


def test_apply_baseline_split():
    f = lint.Finding("ctypes-contract", "x.py", 1, "msg")
    g = lint.Finding("ctypes-contract", "x.py", 2, "other msg")
    new, old = lint.apply_baseline([f, g], {f.id})
    assert new == [g] and old == [f]


# ---- check selection + CLI ----

def test_unknown_check_rejected(tmp_path):
    try:
        _lint_src(tmp_path, "x = 1\n", checks=["no-such-check"])
    except ValueError as e:
        assert "no-such-check" in str(e)
        assert "valid checks" in str(e)
        for name in lint.ALL_CHECKS:
            assert name in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_check_filter(tmp_path):
    src = """\
        lib.brt_x()
    """
    assert _lint_src(tmp_path, src, checks=["obs-guard"]) == []
    assert len(_lint_src(tmp_path, src, checks=["ctypes-contract"])) == 1


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = cwd + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "brpc_tpu.analysis"] + args,
        capture_output=True, text=True, env=env, cwd=cwd)


def test_cli_exit_codes_and_json(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    bad = tmp_path / "viol.py"
    bad.write_text("lib.brt_bad(1)\n")
    proc = _run_cli([str(bad), "--format=json"], cwd=repo)
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["count"] == 1
    (f,) = payload["findings"]
    assert f["check"] == "ctypes-contract" and f["line"] == 1
    assert f["path"].endswith("viol.py")

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _run_cli([str(clean)], cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stderr


def test_cli_text_format_has_file_line(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    bad = tmp_path / "viol.py"
    bad.write_text("\nlib.brt_bad(1)\n")
    proc = _run_cli([str(bad)], cwd=repo)
    assert proc.returncode == 1
    assert f"{bad}:2:" in proc.stdout


def test_cli_unknown_check_exits_2_and_lists_valid_set(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _run_cli([str(clean), "--check", "trace_purity"], cwd=repo)
    assert proc.returncode == 2
    assert "trace_purity" in proc.stderr
    for name in lint.ALL_CHECKS:
        assert name in proc.stderr  # the valid set is listed


def test_cli_baseline_suppression_roundtrip(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    bad = tmp_path / "viol.py"
    bad.write_text("lib.brt_bad(1)\n")
    base = tmp_path / "baseline.json"
    proc = _run_cli([str(bad), "--write-baseline", str(base)], cwd=repo)
    assert proc.returncode == 0, proc.stderr
    # known finding suppressed -> clean exit
    proc = _run_cli([str(bad), "--baseline", str(base)], cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "suppressed by baseline" in proc.stderr
    # a NEW finding still fails even with the baseline applied
    bad.write_text("lib.brt_bad(1)\nlib.brt_worse(2)\n")
    proc = _run_cli([str(bad), "--baseline", str(base), "--format=json"],
                    cwd=repo)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["count"] == 1
    assert payload["suppressed_count"] == 1
    assert "brt_worse" in payload["findings"][0]["message"]


def test_syntax_error_reported_not_crash(tmp_path):
    fs = _lint_src(tmp_path, "def broken(:\n")
    (f,) = fs
    assert f.check == "syntax"


# ---- fiber-blocking-sleep (interprocedural) ----

_SLEEP_HANDLER = """\
    import time

    class S:
        def __init__(self, server):
            server.add_service("X", self._handle)

        def _handle(self, method, req):
            time.sleep(0.5)
            return b""
"""


def test_handler_sleep_flagged(tmp_path):
    fs = _lint_src(tmp_path, _SLEEP_HANDLER)
    (f,) = _by_check(fs, "fiber-blocking-sleep")
    assert "time.sleep" in f.message
    assert "fiber worker" in f.message
    assert "resilience" in f.message


def test_sleep_via_helper_chain_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time

        def pause():
            time.sleep(0.1)

        def work():
            pause()

        class S:
            def __init__(self, server):
                server.add_service("X", self._handle)

            def _handle(self, method, req):
                work()
                return b""
    """)
    (f,) = _by_check(fs, "fiber-blocking-sleep")
    assert "pause" in f.message
    assert "S._handle -> work -> pause" in f.message


def test_sleep_alias_forms_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time as t
        from time import sleep as zzz

        class S:
            def __init__(self, server):
                server.add_service("X", self._handle)

            def _handle(self, method, req):
                t.sleep(1)
                zzz(2)
                return b""
    """)
    fs = _by_check(fs, "fiber-blocking-sleep")
    assert len(fs) == 2
    assert any("imported from time" in f.message for f in fs)


def test_sleep_outside_handlers_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time

        def bench_loop():
            time.sleep(1.0)  # not handler-reachable: fine

        class S:
            def __init__(self, server):
                server.add_service("X", self._handle)

            def _handle(self, method, req):
                return b""
    """)
    assert _by_check(fs, "fiber-blocking-sleep") == []


def test_sleep_via_resilience_helper_clean(tmp_path):
    # The sanctioned path: resilience.sleep_ms — the call into the
    # resilience module is not followed, and a fake sibling named
    # resilience.py proves the cut is by module path, not luck.
    (tmp_path / "brpc_tpu").mkdir()
    (tmp_path / "brpc_tpu" / "__init__.py").write_text("")
    (tmp_path / "brpc_tpu" / "resilience.py").write_text(
        "import time\n\ndef sleep_ms(ms):\n    time.sleep(ms / 1000.0)\n")
    (tmp_path / "brpc_tpu" / "svc.py").write_text(textwrap.dedent("""\
        from brpc_tpu.resilience import sleep_ms

        class S:
            def __init__(self, server):
                server.add_service("X", self._handle)

            def _handle(self, method, req):
                sleep_ms(5)
                return b""
    """))
    fs = lint.run_lint([str(tmp_path / "brpc_tpu")])
    assert _by_check(fs, "fiber-blocking-sleep") == []


def test_async_handler_sleep_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time

        class S:
            def __init__(self, server):
                server.add_async_service("X", self._handle)

            def _handle(self, method, req, respond):
                time.sleep(0.2)
                respond(b"")
    """)
    (f,) = _by_check(fs, "fiber-blocking-sleep")
    assert "S._handle" in f.message


# ---- ctypes-contract: module-scope / global pinning refinements ----

def test_module_level_callback_is_pinned_by_the_module(tmp_path):
    # a module-level CFUNCTYPE def is held by the module namespace for
    # the life of the process — it cannot be GC'd under the native core
    fs = _lint_src(tmp_path, """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None

        @_H
        def dispatch():
            pass

        def install(lib):
            lib.brt_reg(dispatch)
    """)
    assert _by_check(fs, "ctypes-contract") == []


def test_global_assignment_pins_callback(tmp_path):
    good = """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        _ref = None

        def install(lib):
            global _ref

            @_H
            def hook():
                pass
            _ref = hook
            lib.brt_reg(hook)
    """
    assert _by_check(_lint_src(tmp_path, good, name="good.py"),
                     "ctypes-contract") == []
    # without the global pin the function-local callback is still flagged
    bad = textwrap.dedent(good).replace("    global _ref\n", "") \
                               .replace("    _ref = hook\n", "")
    assert bad != textwrap.dedent(good)
    (tmp_path / "good.py").write_text(bad)
    findings = _by_check(lint.lint_files([str(tmp_path / "good.py")]),
                         "ctypes-contract")
    assert len(findings) == 1 and "hook" in findings[0].message


# ---- trace-purity: the allow-trace-impure pragma ----

_TRACED_WITH_COUNTER = """\
    import jax
    from brpc_tpu import obs

    def _count(op):{pragma_def}
        obs.counter(op).add(1)

    def step(x):
        _count("steps"){pragma_call}
        return x

    run = jax.jit(step)
"""


def test_deliberate_trace_time_effect_flagged_without_pragma(tmp_path):
    fs = _lint_src(tmp_path,
                   _TRACED_WITH_COUNTER.format(pragma_def="",
                                               pragma_call=""))
    assert any("obs instrumentation" in f.message
               for f in _by_check(fs, "trace-purity"))


def test_def_level_allow_trace_impure_pragma(tmp_path):
    fs = _lint_src(tmp_path, _TRACED_WITH_COUNTER.format(
        pragma_def="  # lint: allow-trace-impure", pragma_call=""))
    assert _by_check(fs, "trace-purity") == []


def test_call_site_allow_trace_impure_pragma(tmp_path):
    fs = _lint_src(tmp_path, _TRACED_WITH_COUNTER.format(
        pragma_def="", pragma_call="  # lint: allow-trace-impure"))
    assert _by_check(fs, "trace-purity") == []


# ---- lock-order: param-passed locks bound through the call graph ----

_PARAM_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock
    A = checked_lock("pfix.A")
    B = checked_lock("pfix.B")

    def use_inner(lk):
        with lk:
            pass

    def order_ab():
        with A:
            use_inner(B)

    def order_ba():
        with B:
            with A:
                pass
"""


def test_static_lock_order_resolves_param_passed_lock(tmp_path):
    static = _by_check(_lint_src(tmp_path, _PARAM_LOCK_FIXTURE),
                       "lock-order")
    assert len(static) == 1
    assert "pfix.A" in static[0].message and "pfix.B" in static[0].message
    assert "use_inner" in static[0].message  # the chain names the callee


def test_param_passed_lock_matches_dynamic_harness(tmp_path):
    """Parity on the PR-3 blind spot: a lock received as a function
    parameter now resolves statically by binding the caller's argument
    through the call graph — the dynamic harness stays the confirmer."""
    from brpc_tpu.analysis import race

    static = _by_check(_lint_src(tmp_path, _PARAM_LOCK_FIXTURE),
                       "lock-order")
    assert len(static) == 1

    race.clear()
    race.set_enabled(True)
    try:
        ns = {"checked_lock": race.checked_lock}
        src = textwrap.dedent(_PARAM_LOCK_FIXTURE)
        exec(src.split("\n", 1)[1], ns)
        ns["order_ab"]()
        ns["order_ba"]()
        dynamic = [f for f in race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        race.set_enabled(None)
        race.clear()
    assert len(dynamic) == 1
    assert {"pfix.A", "pfix.B"} <= set(dynamic[0].locks)


def test_param_lock_keyword_argument_binds(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock
        A = checked_lock("kw.A")
        B = checked_lock("kw.B")

        def helper(*, lk=None):
            with lk:
                pass

        def outer():
            with B:
                helper(lk=A)

        def reverse():
            with A:
                with B:
                    pass
    """)
    (f,) = _by_check(fs, "lock-order")
    assert "kw.A" in f.message and "kw.B" in f.message


_CONTAINER_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock
    A = checked_lock("cd.A")
    B = checked_lock("cd.B")
    LOCKS = {"a": A, "b": checked_lock("cd.C")}

    def inner():
        with LOCKS["a"]:
            pass

    def outer():
        with B:
            inner()

    def reverse():
        with A:
            with B:
                pass
"""


def test_container_stored_lock_resolves(tmp_path):
    # the last PR-3 lock blind spot, now closed: a lock pulled out of a
    # MODULE-LEVEL LITERAL dict resolves by subscript key — both
    # name-valued ({"a": A}) and direct checked_lock(...) entries
    fs = _lint_src(tmp_path, _CONTAINER_LOCK_FIXTURE)
    (f,) = _by_check(fs, "lock-order")
    assert "cd.A" in f.message and "cd.B" in f.message
    assert "inner" in f.message  # the chain names the callee


def test_container_stored_lock_matches_dynamic_harness(tmp_path):
    """Parity: the container-lock inversion the static pass now reports
    is exactly the one the dynamic harness observes at runtime."""
    from brpc_tpu.analysis import race

    static = _by_check(_lint_src(tmp_path, _CONTAINER_LOCK_FIXTURE),
                       "lock-order")
    assert len(static) == 1

    race.clear()
    race.set_enabled(True)
    try:
        ns = {"checked_lock": race.checked_lock}
        exec(textwrap.dedent(_CONTAINER_LOCK_FIXTURE).split("\n", 1)[1],
             ns)
        ns["outer"]()
        ns["reverse"]()
        dynamic = [f for f in race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        race.set_enabled(None)
        race.clear()
    assert len(dynamic) == 1
    assert {"cd.A", "cd.B"} <= set(dynamic[0].locks)


def test_container_lock_non_constant_key_stays_deferred(tmp_path):
    # a dynamic key cannot bind statically — no false edges, no finding
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock
        A = checked_lock("cdk.A")
        B = checked_lock("cdk.B")
        LOCKS = {"a": A}

        def inner(k):
            with LOCKS[k]:
                pass

        def outer():
            with B:
                inner("a")

        def reverse():
            with A:
                with B:
                    pass
    """)
    assert _by_check(fs, "lock-order") == []


def test_container_lock_mutated_container_stays_deferred(tmp_path):
    # only LITERAL module dicts participate: a container built by
    # subscript stores is not trusted (its contents are runtime state)
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock
        A = checked_lock("cm.A")
        B = checked_lock("cm.B")
        LOCKS = {}
        LOCKS["a"] = A

        def inner():
            with LOCKS["a"]:
                pass

        def outer():
            with B:
                inner()

        def reverse():
            with A:
                with B:
                    pass
    """)
    assert _by_check(fs, "lock-order") == []


# ---- handle-lifecycle ----

_RPC_STUB = """\
    class RpcError(RuntimeError):
        pass


    class PendingCall:
        def __init__(self):
            self._ptr = 1

        def join(self):
            return b""

        def wait(self, t=None):
            return True

        def cancel(self):
            pass

        def close(self):
            pass


    class Stream:
        def __init__(self):
            self._id = 1

        def write(self, data):
            pass

        def close(self):
            pass

        def join(self, timeout_s=None):
            return True

        def abort(self):
            pass


    class Channel:
        def __init__(self, addr):
            self._ptr = 1

        def call_async(self, service, method, request=b""):
            return PendingCall()

        def stream(self, service, method, request=b""):
            return Stream()

        def close(self):
            pass


    class Server:
        def __init__(self):
            self._ptr = 1

        def close(self):
            pass
"""


def _lint_handle_fixture(tmp_path, app_src, name="app.py"):
    (tmp_path / "rpc.py").write_text(textwrap.dedent(_RPC_STUB))
    (tmp_path / name).write_text(textwrap.dedent(app_src))
    return lint.run_lint([str(tmp_path)], checks=["handle-lifecycle"])


def test_dropped_pending_call_flagged(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        def fire_and_forget(ch):
            ch.call_async("Ps", "ApplyGrad", b"x")
    """)
    (f,) = fs
    assert "PendingCall" in f.message and "DROPPED" in f.message
    assert f.line == 2


def test_unclosed_stream_on_early_return_path_flagged(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        import rpc

        def push(addr, flag):
            ch = rpc.Channel(addr)
            st = ch.stream("Ps", "StreamApply")
            if flag:
                ch.close()
                return None
            st.write(b"delta")
            st.close()
            ch.close()
    """)
    (f,) = fs
    assert "Stream 'st'" in f.message and "leaks" in f.message
    assert f.line == 8  # the early return, not the binding


def test_clean_ownership_transfer_is_clean(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        import rpc
        from rpc import Channel


        def make_channel(addr):
            return Channel(addr)


        def round_trip(addr):
            ch = make_channel(addr)
            try:
                pc = ch.call_async("Echo", "M")
                return pc.join()
            finally:
                ch.close()


        class Holder:
            def __init__(self, addr):
                self.ch = rpc.Channel(addr)
                self.srv = rpc.Server()

            def close(self):
                self.ch.close()
                self.srv.close()
    """)
    assert fs == []


def test_inline_consumed_factory_chain_is_clean(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        def call(ch, req):
            return ch.call_async("S", "M", req).join()
    """)
    assert fs == []


def test_attr_store_without_release_method_flagged(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        import rpc


        class LeakyHolder:
            def __init__(self, addr):
                self.ch = rpc.Channel(addr)
    """)
    (f,) = fs
    assert "LeakyHolder.ch" in f.message
    assert "never releases" in f.message


def test_container_escape_flagged_and_pragma_accepted(tmp_path):
    bad = """\
        import rpc

        def pool(addrs):
            out = {}
            for i, a in enumerate(addrs):
                out[i] = rpc.Channel(a)
            return out
    """
    (f,) = _lint_handle_fixture(tmp_path, bad)
    assert "container" in f.message and "allow-handle-escape" in f.message
    good = bad.replace(
        "out[i] = rpc.Channel(a)",
        "out[i] = rpc.Channel(a)  # lint: allow-handle-escape")
    assert _lint_handle_fixture(tmp_path, good) == []


def test_thread_target_escape_flagged(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        import threading

        import rpc

        def spawn(addr):
            ch = rpc.Channel(addr)
            t = threading.Thread(target=worker, args=(ch,))
            t.start()

        def worker(ch):
            pass
    """)
    (f,) = fs
    assert "thread target" in f.message


def test_fall_through_leak_flagged_and_release_any_branch_clean(tmp_path):
    (f,) = _lint_handle_fixture(tmp_path, """\
        import rpc

        def leaky(addr):
            ch = rpc.Channel(addr)
            ch.call_async("S", "M").join()
    """)
    assert "Channel 'ch'" in f.message and "fall-through" in f.message
    # may-leak polarity: a release on SOME branch is trusted (the guard
    # idiom) — no false positive
    assert _lint_handle_fixture(tmp_path, """\
        import rpc

        def guarded(addr, cond):
            ch = rpc.Channel(addr)
            if cond:
                ch.close()
    """) == []


def test_finally_release_covers_returns_inside_try(tmp_path):
    assert _lint_handle_fixture(tmp_path, """\
        import rpc

        def fan_out(addr, reqs):
            group = rpc.Server()
            try:
                for r in reqs:
                    if not r:
                        return None
                return len(reqs)
            finally:
                group.close()
    """) == []


def test_abi_pairing_requires_destroy_symbol(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        lib.brt_widget_new.argtypes = []
        lib.brt_widget_new.restype = ctypes.c_void_p
        lib.brt_widget_new()
    """, checks=["handle-lifecycle"])
    (f,) = fs
    assert "brt_widget_destroy" in f.message
    fixed = _lint_src(tmp_path, """\
        import ctypes
        lib.brt_widget_new.argtypes = []
        lib.brt_widget_new.restype = ctypes.c_void_p
        lib.brt_widget_destroy.argtypes = [ctypes.c_void_p]
        lib.brt_widget_destroy.restype = None
        lib.brt_widget_new()
    """, name="fixed.py", checks=["handle-lifecycle"])
    assert fixed == []


# ---- lock-order: class-scope literal-dict containers ----

_CLASS_CONTAINER_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock

    class Engine:
        LOCKS = {"a": checked_lock("ccd.A"), "b": checked_lock("ccd.B")}

        def fwd(self):
            with self.LOCKS["a"]:
                with self.LOCKS["b"]:
                    pass

        def rev(self):
            with self.LOCKS["b"]:
                with self.LOCKS["a"]:
                    pass
"""


def test_class_container_stored_lock_resolves(tmp_path):
    # `self.LOCKS["a"]` on a CLASS-scope literal dict binds by constant
    # key, same as the module-level container form
    fs = _lint_src(tmp_path, _CLASS_CONTAINER_LOCK_FIXTURE)
    (f,) = _by_check(fs, "lock-order")
    assert "ccd.A" in f.message and "ccd.B" in f.message


def test_class_container_lock_matches_dynamic_harness(tmp_path):
    """Parity: the class-container inversion the static pass now
    reports is exactly the one the dynamic harness observes."""
    import textwrap as _tw

    from brpc_tpu.analysis import race

    static = _by_check(_lint_src(tmp_path,
                                 _CLASS_CONTAINER_LOCK_FIXTURE),
                       "lock-order")
    assert len(static) == 1

    race.clear()
    race.set_enabled(True)
    try:
        ns = {"checked_lock": race.checked_lock}
        exec(_tw.dedent(_CLASS_CONTAINER_LOCK_FIXTURE).split("\n", 1)[1],
             ns)
        eng = ns["Engine"]()
        eng.fwd()
        eng.rev()
        dynamic = [f for f in race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        race.set_enabled(None)
        race.clear()
    assert len(dynamic) == 1
    assert {"ccd.A", "ccd.B"} <= set(dynamic[0].locks)


def test_class_container_non_constant_key_stays_deferred(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu.analysis.race import checked_lock

        class Engine:
            LOCKS = {"a": checked_lock("cck.A")}
            B = None

        OTHER = checked_lock("cck.B")

        def use(eng, k):
            with OTHER:
                with eng.LOCKS[k]:
                    pass

        def reverse(eng):
            with eng.LOCKS["a"]:
                with OTHER:
                    pass
    """)
    assert _by_check(fs, "lock-order") == []


# ---- handle-lifecycle: exception paths (raise = an exit) ----

def test_handle_live_at_raise_flagged(tmp_path):
    fs = _lint_handle_fixture(tmp_path, """\
        import rpc

        def leaky(addr, payload):
            ch = rpc.Channel(addr)
            if not payload:
                raise ValueError("empty payload")
            ch.close()
    """)
    (f,) = fs
    assert "raise (exception path)" in f.message
    assert "'ch'" in f.message and "created line 4" in f.message


def test_handle_released_by_catching_except_clean(tmp_path):
    # the handler that catches the raise releases (and may re-raise
    # after cleanup): the exception path is covered
    assert _lint_handle_fixture(tmp_path, """\
        import rpc

        def covered(addr, payload):
            ch = rpc.Channel(addr)
            try:
                if not payload:
                    raise ValueError("bad")
            except ValueError:
                ch.close()
                raise
            ch.close()
    """) == []


def test_handle_released_by_finally_at_raise_clean(tmp_path):
    assert _lint_handle_fixture(tmp_path, """\
        import rpc

        def covered(addr, payload):
            ch = rpc.Channel(addr)
            try:
                if not payload:
                    raise ValueError("bad")
                return ch.call_async("S", "m").join()
            finally:
                ch.close()
    """) == []


def test_raise_in_else_clause_not_covered_by_handlers(tmp_path):
    # except handlers do NOT catch raises from the else clause: a
    # release that lives only in the handler does not cover this path
    fs = _lint_handle_fixture(tmp_path, """\
        import rpc

        def leaky(addr, payload):
            ch = rpc.Channel(addr)
            try:
                n = len(payload)
            except TypeError:
                ch.close()
                raise
            else:
                if n == 0:
                    raise ValueError("empty")
            ch.close()
    """)
    (f,) = fs
    assert "raise (exception path)" in f.message


def test_raise_after_release_clean(tmp_path):
    assert _lint_handle_fixture(tmp_path, """\
        import rpc

        def strict(addr, payload):
            ch = rpc.Channel(addr)
            if not payload:
                ch.close()
                raise ValueError("empty payload")
            ch.close()
    """) == []


# ---- exception-flow: implicit throws from callees are exits, proven
# ---- statically AND reproduced on the BRPC_TPU_HANDLECHECK ledger ----

import itertools as _it
import types as _types

import pytest

from brpc_tpu.analysis import handles as _handles
from brpc_tpu.analysis import race as _race


def _lint_exc_fixture(tmp_path, app_src, name="app.py"):
    """Static half: handle-lifecycle + the exception-flow tier built on
    the may-throw fixpoint."""
    (tmp_path / "rpc.py").write_text(textwrap.dedent(_RPC_STUB))
    (tmp_path / name).write_text(textwrap.dedent(app_src))
    return lint.run_lint([str(tmp_path)],
                         checks=["handle-lifecycle", "exception-flow"])


def _ledger_rpc_module():
    """An ``rpc`` twin whose owner classes book every construct/release
    in the HANDLECHECK ledger — the runtime half of the static/dynamic
    parity below runs the SAME fixture source against it."""
    seq = _it.count(0x4000)
    mod = _types.ModuleType("rpc")

    class PendingCall:
        def __init__(self):
            self._h = next(seq)
            _handles.note_create("pending", self._h)

        def join(self):
            _handles.note_destroy("pending", self._h)
            return b""

    class Channel:
        def __init__(self, addr):
            self._h = next(seq)
            _handles.note_create("chan", self._h)

        def call_async(self, service, method, request=b""):
            return PendingCall()

        def close(self):
            _handles.note_destroy("chan", self._h)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.close()
            return False

    mod.PendingCall = PendingCall
    mod.Channel = Channel
    return mod


def _ledger_run(fixture, call=None, expect=None):
    """Exec ``fixture`` (its ``import rpc`` redirected to the ledger
    twin), optionally invoke ``call=(fn, *args)`` (expecting ``expect``
    to raise), and return the non-zero live ledger counts — {} means
    every handle the run created was released."""
    _handles.set_enabled(True)
    _handles.clear()
    try:
        ns = {"rpc": _ledger_rpc_module()}
        src = textwrap.dedent(fixture).replace("import rpc\n", "", 1)
        exec(src, ns)
        if call is not None:
            fn, args = call[0], call[1:]
            if expect is not None:
                with pytest.raises(expect):
                    ns[fn](*args)
            else:
                ns[fn](*args)
        return {k: v for k, v in _handles.live_counts().items() if v}
    finally:
        _handles.set_enabled(None)
        _handles.clear()


_IMPLICIT_THROW_FIXTURE = """\
    import rpc

    def parse(payload):
        if not payload:
            raise ValueError("empty frame")
        return payload

    def leaky(addr, payload):
        ch = rpc.Channel(addr)
        body = parse(payload)
        ch.close()
        return body
"""


def test_implicit_throw_leak_static(tmp_path):
    # the handle leaks ONLY via the callee's raise — no explicit raise,
    # return, or fall-through in sight of the old per-statement pass
    (f,) = _lint_exc_fixture(tmp_path, _IMPLICIT_THROW_FIXTURE)
    assert f.check == "exception-flow"
    assert f.line == 10          # the throwing call, not the binding
    assert "'ch'" in f.message and "ValueError" in f.message
    assert "unwinding edge" in f.message


def test_implicit_throw_leak_dynamic_ledger():
    live = _ledger_run(_IMPLICIT_THROW_FIXTURE,
                       call=("leaky", "addr", b""), expect=ValueError)
    assert live.get("chan") == 1   # the ledger reproduces the leak


_IMPLICIT_THROW_FIXED = """\
    import rpc

    def parse(payload):
        if not payload:
            raise ValueError("empty frame")
        return payload

    def fin(addr, payload):
        ch = rpc.Channel(addr)
        try:
            return parse(payload)
        finally:
            ch.close()

    def ctx(addr, payload):
        with rpc.Channel(addr) as ch:
            return parse(payload)
"""


def test_implicit_throw_finally_and_with_clean_static(tmp_path):
    assert _lint_exc_fixture(tmp_path, _IMPLICIT_THROW_FIXED) == []


def test_implicit_throw_finally_and_with_clean_dynamic():
    for fn in ("fin", "ctx"):
        live = _ledger_run(_IMPLICIT_THROW_FIXED,
                           call=(fn, "addr", b""), expect=ValueError)
        assert live == {}, (fn, live)


_OVERTRUST_FIXTURE = """\
    import rpc

    def parse(payload):
        if not payload:
            raise ValueError("empty frame")
        return payload

    def overtrusting(addr, payload):
        ch = rpc.Channel(addr)
        try:
            size = len(payload)
        except TypeError:
            ch.close()
            raise
        body = parse(payload)
        ch.close()
        return body
"""


def test_handler_trust_scoped_to_its_own_try_static(tmp_path):
    # a release inside SOME handler no longer blesses the whole
    # function: the throwing call sits outside that handler's try
    (f,) = _lint_exc_fixture(tmp_path, _OVERTRUST_FIXTURE)
    assert f.check == "exception-flow"
    assert f.line == 15
    assert "ValueError" in f.message


def test_handler_trust_scoped_dynamic_ledger():
    live = _ledger_run(_OVERTRUST_FIXTURE,
                       call=("overtrusting", "addr", b""),
                       expect=ValueError)
    assert live.get("chan") == 1


def test_handler_covering_call_and_type_clean(tmp_path):
    covered = """\
        import rpc

        def parse(payload):
            if not payload:
                raise ValueError("empty frame")
            return payload

        def covered(addr, payload):
            ch = rpc.Channel(addr)
            try:
                body = parse(payload)
            except ValueError:
                ch.close()
                raise
            ch.close()
            return body
    """
    assert _lint_exc_fixture(tmp_path, covered) == []
    live = _ledger_run(covered, call=("covered", "addr", b""),
                       expect=ValueError)
    assert live == {}


def test_handler_of_wrong_type_does_not_cover(tmp_path):
    (f,) = _lint_exc_fixture(tmp_path, """\
        import rpc

        def parse(payload):
            if not payload:
                raise ValueError("empty frame")
            return payload

        def wrong(addr, payload):
            ch = rpc.Channel(addr)
            try:
                body = parse(payload)
            except OSError:
                ch.close()
                raise
            ch.close()
            return body
    """)
    assert f.check == "exception-flow"
    assert f.line == 11


def test_handler_catches_base_class_of_thrown_type(tmp_path):
    # LookupError covers KeyError through the builtin hierarchy
    assert _lint_exc_fixture(tmp_path, """\
        import rpc

        def pick(table, key):
            return table[key] if key in table else _boom(key)

        def _boom(key):
            raise KeyError(key)

        def covered(addr, table, key):
            ch = rpc.Channel(addr)
            try:
                row = pick(table, key)
            except LookupError:
                ch.close()
                raise
            ch.close()
            return row
    """) == []


_CONTAINER_ESCAPE_FIXTURE = """\
    import rpc

    def burst(addr, n):
        ch = rpc.Channel(addr)
        calls = []
        for _i in range(n):
            calls.append(ch.call_async("Ps", "Apply"))
        ch.close()
"""


def test_container_may_leak_set_static(tmp_path):
    (f,) = _lint_exc_fixture(tmp_path, _CONTAINER_ESCAPE_FIXTURE)
    assert f.check == "handle-lifecycle"
    assert "container 'calls'" in f.message
    assert "never drained" in f.message


def test_container_may_leak_set_dynamic_ledger():
    live = _ledger_run(_CONTAINER_ESCAPE_FIXTURE, call=("burst", "a", 3))
    assert live.get("pending") == 3


_CONTAINER_DRAINED_FIXTURE = """\
    import rpc

    def burst(addr, n):
        ch = rpc.Channel(addr)
        calls = []
        for _i in range(n):
            calls.append(ch.call_async("Ps", "Apply"))
        for pc in calls:
            pc.join()
        ch.close()
"""


def test_container_drained_clean_both_ways(tmp_path):
    assert _lint_exc_fixture(tmp_path, _CONTAINER_DRAINED_FIXTURE) == []
    assert _ledger_run(_CONTAINER_DRAINED_FIXTURE,
                       call=("burst", "a", 3)) == {}


def test_container_returned_or_pragmad_clean(tmp_path):
    returned = _CONTAINER_ESCAPE_FIXTURE.replace(
        "        ch.close()",
        "        ch.close()\n        return calls")
    assert _lint_exc_fixture(tmp_path, returned) == []
    pragmad = _CONTAINER_ESCAPE_FIXTURE.replace(
        'calls.append(ch.call_async("Ps", "Apply"))',
        'calls.append(ch.call_async("Ps", "Apply"))'
        '  # lint: allow-handle-escape')
    assert _lint_exc_fixture(tmp_path, pragmad) == []


_REBIND_FIXTURE = """\
    import rpc

    def reconnect(addr, backup):
        ch = rpc.Channel(addr)
        ch = rpc.Channel(backup)
        ch.close()
"""


def test_rebind_drop_static(tmp_path):
    (f,) = _lint_exc_fixture(tmp_path, _REBIND_FIXTURE)
    assert f.check == "handle-lifecycle"
    assert "rebinding 'ch'" in f.message
    assert f.line == 5


def test_rebind_drop_dynamic_ledger():
    live = _ledger_run(_REBIND_FIXTURE, call=("reconnect", "a", "b"))
    assert live.get("chan") == 1   # the first channel has no name left


def test_rebind_after_release_clean(tmp_path):
    fixed = """\
        import rpc

        def reconnect(addr, backup):
            ch = rpc.Channel(addr)
            ch.close()
            ch = rpc.Channel(backup)
            ch.close()
    """
    assert _lint_exc_fixture(tmp_path, fixed) == []
    assert _ledger_run(fixed, call=("reconnect", "a", "b")) == {}


_MODULE_SCOPE_FIXTURE = """\
    import rpc

    CH = rpc.Channel("127.0.0.1:9999")
"""


def test_module_scope_producer_static(tmp_path):
    (f,) = _lint_exc_fixture(tmp_path, _MODULE_SCOPE_FIXTURE)
    assert f.check == "handle-lifecycle"
    assert "module-scope" in f.message and "'CH'" in f.message


def test_module_scope_producer_dynamic_ledger():
    # the handle is live from import time with no release path
    live = _ledger_run(_MODULE_SCOPE_FIXTURE)
    assert live.get("chan") == 1


def test_module_scope_producer_with_shutdown_clean(tmp_path):
    fixed = _MODULE_SCOPE_FIXTURE + \
        "\n\n    def shutdown():\n        CH.close()\n"
    assert _lint_exc_fixture(tmp_path, fixed) == []
    assert _ledger_run(fixed, call=("shutdown",)) == {}


def test_module_scope_singleton_pragma_accepted(tmp_path):
    pragmad = _MODULE_SCOPE_FIXTURE.replace(
        'CH = rpc.Channel("127.0.0.1:9999")',
        'CH = rpc.Channel("127.0.0.1:9999")  # lint: allow-handle-escape')
    assert _lint_exc_fixture(tmp_path, pragmad) == []


# ---- lock-exception-safety: locks and obligations on unwinding edges ----

_LOCK_MANUAL_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock

    MU = checked_lock("lxs.MU")

    def risky(payload):
        if not payload:
            raise ValueError("empty")
        return payload

    def unsafe(payload):
        MU.acquire()
        body = risky(payload)
        MU.release()
        return body
"""


def test_manual_lock_across_throw_static(tmp_path):
    (f,) = _lint_src(tmp_path, _LOCK_MANUAL_FIXTURE,
                     checks=["lock-exception-safety"])
    assert f.check == "lock-exception-safety"
    assert "lxs.MU" in f.message and "may-throw" in f.message
    assert f.line == 12


def test_manual_lock_across_throw_dynamic_parity():
    ns = {"checked_lock": _race.checked_lock}
    exec(textwrap.dedent(_LOCK_MANUAL_FIXTURE).split("\n", 1)[1], ns)
    with pytest.raises(ValueError):
        ns["unsafe"](b"")
    assert ns["MU"].locked()   # left locked forever on the unwind
    ns["MU"].release()


_LOCK_FIXED_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock

    MU = checked_lock("lxf.MU")

    def risky(payload):
        if not payload:
            raise ValueError("empty")
        return payload

    def paired(payload):
        MU.acquire()
        try:
            return risky(payload)
        finally:
            MU.release()

    def scoped(payload):
        with MU:
            return risky(payload)
"""


def test_lock_release_in_finally_or_with_clean(tmp_path):
    assert _lint_src(tmp_path, _LOCK_FIXED_FIXTURE,
                     checks=["lock-exception-safety"]) == []
    ns = {"checked_lock": _race.checked_lock}
    exec(textwrap.dedent(_LOCK_FIXED_FIXTURE).split("\n", 1)[1], ns)
    for fn in ("paired", "scoped"):
        with pytest.raises(ValueError):
            ns[fn](b"")
        assert not ns["MU"].locked(), fn


_FENCE_FIXTURE = """\
    class Shard:
        def risky(self, payload):
            if not payload:
                raise ValueError("empty")
            return payload

        def fenced_apply(self, payload):
            self._fencing = True
            body = self.risky(payload)
            self._fencing = False
            return body
"""


def test_fence_flag_half_done_on_unwind_static(tmp_path):
    (f,) = _lint_src(tmp_path, _FENCE_FIXTURE,
                     checks=["lock-exception-safety"])
    assert f.check == "lock-exception-safety"
    assert "_fencing" in f.message and "finally" in f.message
    assert f.line == 9


def test_fence_flag_half_done_on_unwind_dynamic():
    ns = {}
    exec(textwrap.dedent(_FENCE_FIXTURE), ns)
    sh = ns["Shard"]()
    with pytest.raises(ValueError):
        sh.fenced_apply(b"")
    assert sh._fencing is True   # the half-done obligation, observable


def test_fence_flag_reset_in_finally_clean(tmp_path):
    fixed = """\
        class Shard:
            def risky(self, payload):
                if not payload:
                    raise ValueError("empty")
                return payload

            def fenced_apply(self, payload):
                self._fencing = True
                try:
                    return self.risky(payload)
                finally:
                    self._fencing = False
    """
    assert _lint_src(tmp_path, fixed,
                     checks=["lock-exception-safety"]) == []
    ns = {}
    exec(textwrap.dedent(fixed), ns)
    sh = ns["Shard"]()
    with pytest.raises(ValueError):
        sh.fenced_apply(b"")
    assert sh._fencing is False


# ---- lock-order: class containers inherited from base classes ----

_INHERITED_CONTAINER_LOCK_FIXTURE = """\
    from brpc_tpu.analysis.race import checked_lock

    class Base:
        LOCKS = {"a": checked_lock("mro.A"), "b": checked_lock("mro.B")}

    class Engine(Base):
        def fwd(self):
            with self.LOCKS["a"]:
                with self.LOCKS["b"]:
                    pass

        def rev(self):
            with self.LOCKS["b"]:
                with self.LOCKS["a"]:
                    pass
"""


def test_inherited_class_container_lock_resolves(tmp_path):
    # the container lives on Base; the inversion is in the subclass —
    # the base-chain walk binds self.LOCKS["a"] through the MRO
    fs = _lint_src(tmp_path, _INHERITED_CONTAINER_LOCK_FIXTURE)
    (f,) = _by_check(fs, "lock-order")
    assert "mro.A" in f.message and "mro.B" in f.message


def test_inherited_container_lock_matches_dynamic_harness(tmp_path):
    static = _by_check(
        _lint_src(tmp_path, _INHERITED_CONTAINER_LOCK_FIXTURE),
        "lock-order")
    assert len(static) == 1

    _race.clear()
    _race.set_enabled(True)
    try:
        ns = {"checked_lock": _race.checked_lock}
        exec(textwrap.dedent(
            _INHERITED_CONTAINER_LOCK_FIXTURE).split("\n", 1)[1], ns)
        eng = ns["Engine"]()
        eng.fwd()
        eng.rev()
        dynamic = [f for f in _race.findings()
                   if f.kind == "lock-inversion"]
    finally:
        _race.set_enabled(None)
        _race.clear()
    assert len(dynamic) == 1
    assert {"mro.A", "mro.B"} <= set(dynamic[0].locks)


def test_inherited_container_shadowed_nonliteral_stays_deferred(tmp_path):
    shadowed = _INHERITED_CONTAINER_LOCK_FIXTURE.replace(
        "class Engine(Base):",
        "class Engine(Base):\n    LOCKS = dict(Base.LOCKS)")
    assert _by_check(_lint_src(tmp_path, shadowed), "lock-order") == []


def test_inherited_container_mutated_in_subclass_stays_deferred(tmp_path):
    mutated = _INHERITED_CONTAINER_LOCK_FIXTURE.replace(
        "    def fwd(self):",
        "    def grow(self):\n"
        "        self.LOCKS[\"c\"] = checked_lock(\"mro.C\")\n"
        "\n"
        "    def fwd(self):")
    assert _by_check(_lint_src(tmp_path, mutated), "lock-order") == []
