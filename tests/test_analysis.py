"""Unit tests for the framework-invariant linter (brpc_tpu.analysis.lint):
each check must fire on a seeded violation and stay quiet on the fixed
form of the same code."""

import json
import os
import subprocess
import sys
import textwrap

from brpc_tpu.analysis import lint


def _lint_src(tmp_path, src, name="mod.py", checks=None):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return lint.lint_files([str(p)], checks)


def _by_check(findings, check):
    return [f for f in findings if f.check == check]


# ---- ctypes-contract: argtypes/restype ----

def test_undeclared_brt_symbol_flagged(tmp_path):
    fs = _lint_src(tmp_path, "lib.brt_mystery(1)\n")
    (f,) = _by_check(fs, "ctypes-contract")
    assert "brt_mystery" in f.message
    assert "argtypes and restype" in f.message
    assert f.line == 1


def test_partial_declaration_flags_missing_restype(tmp_path):
    fs = _lint_src(tmp_path, """\
        lib.brt_thing.argtypes = []
        lib.brt_thing(1)
    """)
    (f,) = _by_check(fs, "ctypes-contract")
    assert "restype" in f.message and "argtypes and" not in f.message


def test_fully_declared_symbol_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        lib.brt_ok.argtypes = [ctypes.c_int]
        lib.brt_ok.restype = ctypes.c_void_p
        lib.brt_ok(1)
    """)
    assert fs == []


def test_declaration_in_sibling_file_counts(tmp_path):
    (tmp_path / "decls.py").write_text(
        "lib.brt_shared.argtypes = []\nlib.brt_shared.restype = None\n")
    (tmp_path / "use.py").write_text("x._lib.brt_shared()\n")
    assert lint.run_lint([str(tmp_path)]) == []


# ---- ctypes-contract: CFUNCTYPE pinning ----

def test_inline_cfunctype_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        def register(lib, cb):
            lib.brt_reg(_H(cb))
    """)
    (f,) = _by_check(fs, "ctypes-contract")
    assert "inline" in f.message and "GC" in f.message


def test_unpinned_callback_flagged_and_pinned_clean(tmp_path):
    bad = """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        class S:
            def add(self, lib):
                @_H
                def tramp():
                    pass
                lib.brt_reg(tramp)
    """
    fs = _lint_src(tmp_path, bad, name="bad.py")
    (f,) = _by_check(fs, "ctypes-contract")
    assert "tramp" in f.message and "pinned" in f.message

    good = bad.replace("lib.brt_reg(tramp)",
                       "lib.brt_reg(tramp)\n"
                       "                self._handlers.append(tramp)")
    assert _lint_src(tmp_path, good, name="good.py") == []


def test_attribute_pinning_counts(tmp_path):
    fs = _lint_src(tmp_path, """\
        import ctypes
        _H = ctypes.CFUNCTYPE(None)
        lib.brt_reg.argtypes = [_H]
        lib.brt_reg.restype = None
        class S:
            def add(self, lib):
                cb = _H(lambda: None)
                self._cb = cb
                lib.brt_reg(cb)
    """)
    assert fs == []


# ---- fiber-shared-state ----

_HANDLER_CLASS = """\
    import threading

    class Shard:
        def __init__(self, server):
            self._mu = threading.Lock()
            self.count = 0
            server.add_service("Ps", self._handle)

        def _handle(self, method, req):
            {body}
            return b""
"""


def test_unlocked_handler_mutation_flagged(tmp_path):
    fs = _lint_src(tmp_path,
                   _HANDLER_CLASS.format(body="self.count += 1"))
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "Shard._handle" in f.message and "self.count" in f.message


def test_locked_handler_mutation_clean(tmp_path):
    fs = _lint_src(tmp_path, _HANDLER_CLASS.format(
        body="with self._mu:\n                self.count += 1"))
    assert _by_check(fs, "fiber-shared-state") == []


def test_ufunc_at_mutation_flagged(tmp_path):
    fs = _lint_src(tmp_path, _HANDLER_CLASS.format(
        body="np.subtract.at(self.table, req, 1)"))
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "self.table" in f.message


def test_mutation_via_helper_method_flagged(tmp_path):
    src = """\
        class Shard:
            def __init__(self, server):
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                self._serve(req)
                return b""

            def _serve(self, req):
                self.rows.append(req)
    """
    fs = _lint_src(tmp_path, src)
    (f,) = _by_check(fs, "fiber-shared-state")
    assert "Shard._serve" in f.message


def test_helper_only_called_under_lock_clean(tmp_path):
    src = """\
        import threading

        class Shard:
            def __init__(self, server):
                self._mu = threading.Lock()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                with self._mu:
                    self._serve(req)
                return b""

            def _serve(self, req):
                self.rows = req
    """
    assert _lint_src(tmp_path, src) == []


def test_non_handler_class_not_audited(tmp_path):
    src = """\
        class Plain:
            def poke(self):
                self.count = 1
    """
    assert _lint_src(tmp_path, src) == []


# ---- obs-guard ----

def test_direct_registry_use_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu import obs

        def hot(n):
            obs.counter("x").add(n)      # allowed: no-op-able helper
            a = obs.Adder()              # direct reducer construction
            obs.default_registry()       # direct registry access
            obs.expose("y", a)           # direct expose
    """)
    fs = _by_check(fs, "obs-guard")
    assert len(fs) == 3
    assert all("no-op-able" in f.message for f in fs)


def test_obs_package_itself_exempt(tmp_path):
    fs = _lint_src(tmp_path, """\
        from brpc_tpu import obs
        obs.Adder()
    """, name=os.path.join("obs", "inner.py"))
    assert _by_check(fs, "obs-guard") == []


# ---- trace-purity ----

def test_impure_jit_function_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        import time
        import jax
        from functools import partial
        from brpc_tpu import obs

        @jax.jit
        def step(x):
            print(x)
            t = time.time()
            return x + t

        @partial(jax.jit, static_argnames=())
        def counted(x):
            obs.counter("steps").add(1)
            return x

        traced = jax.jit(lambda x: print(x))
    """)
    fs = _by_check(fs, "trace-purity")
    assert len(fs) == 4
    kinds = " | ".join(f.message for f in fs)
    assert "print" in kinds and "time.time" in kinds and "obs" in kinds


def test_shard_map_lock_flagged(tmp_path):
    fs = _lint_src(tmp_path, """\
        from functools import partial
        from brpc_tpu._compat import shard_map

        class C:
            def op(self, x):
                @partial(shard_map, mesh=self.mesh, in_specs=None,
                         out_specs=None)
                def _f(shard):
                    with self._mu:
                        return shard
                return _f(x)
    """)
    (f,) = _by_check(fs, "trace-purity")
    assert "lock" in f.message


def test_pure_jit_function_clean(tmp_path):
    fs = _lint_src(tmp_path, """\
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x * 2)
    """)
    assert fs == []


# ---- check selection + CLI ----

def test_unknown_check_rejected(tmp_path):
    try:
        _lint_src(tmp_path, "x = 1\n", checks=["no-such-check"])
    except ValueError as e:
        assert "no-such-check" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_check_filter(tmp_path):
    src = """\
        lib.brt_x()
    """
    assert _lint_src(tmp_path, src, checks=["obs-guard"]) == []
    assert len(_lint_src(tmp_path, src, checks=["ctypes-contract"])) == 1


def _run_cli(args, cwd):
    env = dict(os.environ)
    env["PYTHONPATH"] = cwd + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "brpc_tpu.analysis"] + args,
        capture_output=True, text=True, env=env, cwd=cwd)


def test_cli_exit_codes_and_json(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    bad = tmp_path / "viol.py"
    bad.write_text("lib.brt_bad(1)\n")
    proc = _run_cli([str(bad), "--format=json"], cwd=repo)
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["count"] == 1
    (f,) = payload["findings"]
    assert f["check"] == "ctypes-contract" and f["line"] == 1
    assert f["path"].endswith("viol.py")

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _run_cli([str(clean)], cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stderr


def test_cli_text_format_has_file_line(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(lint.__file__))))
    bad = tmp_path / "viol.py"
    bad.write_text("\nlib.brt_bad(1)\n")
    proc = _run_cli([str(bad)], cwd=repo)
    assert proc.returncode == 1
    assert f"{bad}:2:" in proc.stdout


def test_syntax_error_reported_not_crash(tmp_path):
    fs = _lint_src(tmp_path, "def broken(:\n")
    (f,) = fs
    assert f.check == "syntax"
