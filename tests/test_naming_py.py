"""Python-tier service discovery over the native naming registry: a brt
server hosts the registry (C API), shards register with TTL heartbeats,
and RemoteEmbedding resolves its shard list from the cluster — no static
addresses (cpp/cluster/remote_naming.h through the JSON bridge)."""

import threading
import time

import numpy as np

from brpc_tpu import rpc
from brpc_tpu.naming import NamingClient
from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

VOCAB, DIM = 32, 8


def test_registry_register_list_watch():
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    port = reg_server.start("127.0.0.1:0")
    reg = NamingClient(f"127.0.0.1:{port}")

    v = reg.register("c1", "10.0.0.1:100", heartbeat=False)
    assert v >= 1
    nodes, version = reg.list("c1")
    assert [n["addr"] for n in nodes] == ["10.0.0.1:100"]

    # Watch blocks until a later registration bumps the version.
    t0 = time.monotonic()
    result = {}

    def registrar():
        time.sleep(0.3)
        reg2 = NamingClient(f"127.0.0.1:{port}")
        reg2.register("c1", "10.0.0.2:100", heartbeat=False)
        result["registered_at"] = time.monotonic()

    th = threading.Thread(target=registrar)
    th.start()
    nodes, version2 = reg.watch("c1", known_version=version, wait_ms=5000)
    blocked_s = time.monotonic() - t0
    th.join()
    assert version2 > version
    assert len(nodes) == 2
    assert blocked_s >= 0.25, f"watch returned too early ({blocked_s}s)"

    # TTL lapse without heartbeat drops the node.
    reg.register("c2", "10.0.0.3:1", ttl_ms=400, heartbeat=False)
    time.sleep(0.8)
    nodes, _ = reg.list("c2")
    assert nodes == []

    # With heartbeats the entry survives several TTL windows.
    reg.register("c3", "10.0.0.4:1", ttl_ms=400, heartbeat=True)
    time.sleep(1.2)
    nodes, _ = reg.list("c3")
    assert [n["addr"] for n in nodes] == ["10.0.0.4:1"]
    reg.close()
    reg_server.close()


def test_remote_embedding_from_registry():
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    port = reg_server.start("127.0.0.1:0")
    registry = f"127.0.0.1:{port}"

    shards = [PsShardServer(VOCAB, DIM, s, 2, lr=0.5) for s in range(2)]
    reg = NamingClient(registry)
    for s_idx, s in enumerate(shards):
        reg.register("ps", s.address, tag=f"{s_idx}/2", ttl_ms=5000)

    emb = RemoteEmbedding.from_registry(registry, "ps", VOCAB, DIM)
    assert emb.n == 2

    # Owner routing works across the discovered shards; training converges.
    ids = np.array([1, 5, 17, 29], np.int32)
    target = np.zeros((4, DIM), np.float32)
    rows = emb.lookup(ids)
    assert rows.shape == (4, DIM)
    np.testing.assert_allclose(rows[0], shards[0].table[1], rtol=1e-6)
    np.testing.assert_allclose(rows[2], shards[1].table[1], rtol=1e-6)
    first = float(((rows - target) ** 2).mean())
    for _ in range(5):
        rows = emb.lookup(ids)
        emb.apply_gradients(ids, rows - target)
    final = float(((emb.lookup(ids) - target) ** 2).mean())
    assert final < first

    emb.close()
    reg.close()
    for s in shards:
        s.close()
    reg_server.close()


def test_from_registry_times_out_on_incomplete_cluster():
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    port = reg_server.start("127.0.0.1:0")
    registry = f"127.0.0.1:{port}"
    reg = NamingClient(registry)
    reg.register("partial", "10.0.0.9:1", tag="0/2", heartbeat=False)
    try:
        RemoteEmbedding.from_registry(registry, "partial", VOCAB, DIM,
                                      wait_ms=800)
        raise AssertionError("expected TimeoutError")
    except TimeoutError:
        pass
    reg.close()
    reg_server.close()
