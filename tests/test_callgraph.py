"""Unit tests for the whole-package call-graph resolver
(brpc_tpu.analysis.callgraph) and the interprocedural lint passes built
on it: cross-module edges, method resolution through self, partial
targets, cycle tolerance — plus seeded cross-module violations that the
old per-file lexical pass provably misses but the call-graph pass
reports with the full call chain."""

import ast
import textwrap

from brpc_tpu.analysis import lint
from brpc_tpu.analysis.callgraph import (build_callgraph,
                                         module_name_for_path)


def _graph(tmp_path, **files):
    pairs = []
    for name, src in files.items():
        p = tmp_path / f"{name}.py"
        p.write_text(textwrap.dedent(src))
        pairs.append((str(p), ast.parse(textwrap.dedent(src))))
    return build_callgraph(pairs)


def _only_node(g, suffix):
    hits = [nid for nid in g.nodes if nid.endswith(suffix)]
    assert len(hits) == 1, (suffix, sorted(g.nodes))
    return hits[0]


def _callee_ids(g, node_id):
    return sorted({s.callee for s in g.callees(node_id)})


# ---- resolver: edges ----

def test_cross_module_edges_from_import_and_alias(tmp_path):
    g = _graph(
        tmp_path,
        helpers="""\
            def shared():
                pass
        """,
        a="""\
            from helpers import shared

            def caller():
                shared()
        """,
        b="""\
            import helpers

            def caller2():
                helpers.shared()
        """,
    )
    shared = _only_node(g, ":shared")
    assert _callee_ids(g, _only_node(g, ":caller")) == [shared]
    assert _callee_ids(g, _only_node(g, ":caller2")) == [shared]


def test_method_resolution_through_self_and_base(tmp_path):
    g = _graph(tmp_path, m="""\
        class Base:
            def inherited(self):
                pass

        class Impl(Base):
            def entry(self):
                self.helper()
                self.inherited()

            def helper(self):
                pass
    """)
    entry = _only_node(g, "Impl.entry")
    assert _callee_ids(g, entry) == sorted([
        _only_node(g, "Base.inherited"), _only_node(g, "Impl.helper")])


def test_constructor_edge_including_inherited_init(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class Base:
                def __init__(self):
                    pass

            class Widget(Base):
                pass
        """,
        use="""\
            from lib import Widget

            def make():
                return Widget()
        """,
    )
    assert _callee_ids(g, _only_node(g, ":make")) == \
        [_only_node(g, "Base.__init__")]


def test_partial_targets(tmp_path):
    g = _graph(tmp_path, m="""\
        from functools import partial

        def worker(a, b):
            pass

        bound = partial(worker, 1)

        def runner():
            h = partial(worker, 2)
            h(3)

        def direct():
            partial(worker, 4)(5)
    """)
    worker = _only_node(g, ":worker")
    assert worker in _callee_ids(g, _only_node(g, ":runner"))
    assert worker in _callee_ids(g, _only_node(g, ":direct"))
    # the module-level alias resolves for callers too
    assert g.modules[next(iter(g.modules))].partial_aliases["bound"] == worker


def test_nested_function_edges(tmp_path):
    g = _graph(tmp_path, m="""\
        def outer():
            def inner():
                leaf()
            inner()

        def leaf():
            pass
    """)
    outer = _only_node(g, ":outer")
    inner = _only_node(g, "outer.inner")
    assert inner in _callee_ids(g, outer)
    assert _only_node(g, ":leaf") in _callee_ids(g, inner)


def test_cycle_tolerance(tmp_path):
    g = _graph(tmp_path, m="""\
        def ping():
            pong()

        def pong():
            ping()
    """)
    ping = _only_node(g, ":ping")
    reach = g.reachable(ping)
    assert ping in reach and _only_node(g, ":pong") in reach
    assert len(reach) == 2  # terminated despite the cycle


def test_module_name_for_path(tmp_path):
    pkg = tmp_path / "pkg" / "sub"
    pkg.mkdir(parents=True)
    (tmp_path / "pkg" / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text("")
    assert module_name_for_path(str(pkg / "mod.py")) == "pkg.sub.mod"
    assert module_name_for_path(str(pkg / "__init__.py")) == "pkg.sub"
    lone = tmp_path / "lone.py"
    lone.write_text("")
    assert module_name_for_path(str(lone)) == "lone"


# ---- attr-type map: calls on held objects (self.<attr> = Class(...)) ----

def test_held_object_method_edge_resolves(tmp_path):
    """The PR-3 deferral: ``self.dev.stage()`` used to be a skipped edge;
    the ``self.<attr> = Class(...)`` type map resolves it."""
    g = _graph(tmp_path, m="""\
        class Dev:
            def stage(self):
                pass

        class Shard:
            def __init__(self):
                self.dev = Dev()

            def handle(self):
                self.dev.stage()
    """)
    assert _only_node(g, "Dev.stage") in \
        _callee_ids(g, _only_node(g, "Shard.handle"))


def test_held_object_edge_across_modules_and_alias(tmp_path):
    g = _graph(
        tmp_path,
        rpclib="""\
            class DeviceClient:
                def fetch(self):
                    pass
        """,
        app="""\
            import rpclib

            class Server:
                def __init__(self, client=None):
                    self.dev = client or rpclib.DeviceClient()

                def handle(self):
                    self.dev.fetch()
        """,
    )
    # the `x or Class()` injectable-dependency default resolves too
    assert _only_node(g, "DeviceClient.fetch") in \
        _callee_ids(g, _only_node(g, "Server.handle"))


def test_held_object_ambiguous_attr_stays_unresolved(tmp_path):
    """An attr constructed as two different classes would make any edge a
    guess — the under-approximation polarity drops it."""
    g = _graph(tmp_path, m="""\
        class A:
            def go(self):
                pass

        class B:
            def go(self):
                pass

        class User:
            def __init__(self, fast):
                if fast:
                    self.impl = A()
                else:
                    self.impl = B()

            def handle(self):
                self.impl.go()
    """)
    assert _callee_ids(g, _only_node(g, "User.handle")) == []


def test_held_object_mutation_reaches_fiber_shared_state(tmp_path):
    """A handler mutating state THROUGH a held object was invisible to the
    resolver before the attr-type map; now the chain is followed and the
    unlocked mutation inside the held class is reported."""
    (tmp_path / "app.py").write_text(textwrap.dedent("""\
        class Sink:
            def __init__(self):
                self.items = []

            def push(self, x):
                self.items.append(x)

        class Shard:
            def __init__(self, server):
                self.sink = Sink()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                self.sink.push(req)
                return b""
    """))
    findings = [f for f in lint.run_lint([str(tmp_path)])
                if f.check == "fiber-shared-state"]
    assert len(findings) == 1
    f = findings[0]
    assert "self.items" in f.message
    assert "Shard._handle -> Sink.push" in f.message


def test_constructor_self_mutation_exempt(tmp_path):
    """__init__ initializing its OWN fresh object is not shared-state
    mutation (nothing else can see the object before publication) — the
    attr-type map makes constructors handler-reachable, so the check must
    not flag them."""
    (tmp_path / "app.py").write_text(textwrap.dedent("""\
        class Item:
            def __init__(self, v):
                self.v = v

        class Shard:
            def __init__(self, server):
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                return Item(req).v
    """))
    assert lint.run_lint([str(tmp_path)]) == []


# ---- seeded cross-module violations the lexical pass misses ----

_IMPURE_HELPERS = """\
    import time

    def stamp(x):
        return deeper(x)

    def deeper(x):
        return x + time.time()
"""

_TRACED_APP = """\
    import jax
    from helpers import stamp

    @jax.jit
    def step(x):
        return stamp(x)
"""


def test_cross_module_trace_purity_with_chain(tmp_path):
    (tmp_path / "helpers.py").write_text(textwrap.dedent(_IMPURE_HELPERS))
    (tmp_path / "app.py").write_text(textwrap.dedent(_TRACED_APP))
    # the old lexical shape — scanning app.py alone — sees nothing
    assert lint.run_lint([str(tmp_path / "app.py")]) == []
    # the whole-package pass follows the chain into the other module
    findings = [f for f in lint.run_lint([str(tmp_path)])
                if f.check == "trace-purity"]
    assert len(findings) == 1
    f = findings[0]
    assert f.path.endswith("helpers.py")
    assert "time.time" in f.message
    assert "step -> stamp -> deeper" in f.message  # the full call chain


_SHARED_HELPERS = """\
    PENDING = []

    def enqueue(item):
        PENDING.append(item)
"""

_HANDLER_APP = """\
    from helpers import enqueue

    class Shard:
        def __init__(self, server):
            server.add_service("Ps", self._handle)

        def _handle(self, method, req):
            enqueue(req)
            return b""
"""


def test_cross_module_fiber_shared_state_with_chain(tmp_path):
    (tmp_path / "helpers.py").write_text(textwrap.dedent(_SHARED_HELPERS))
    (tmp_path / "app.py").write_text(textwrap.dedent(_HANDLER_APP))
    assert lint.run_lint([str(tmp_path / "app.py")]) == []
    findings = [f for f in lint.run_lint([str(tmp_path)])
                if f.check == "fiber-shared-state"]
    assert len(findings) == 1
    f = findings[0]
    assert f.path.endswith("helpers.py")
    assert "PENDING" in f.message
    assert "Shard._handle -> enqueue" in f.message


def test_cross_module_helper_called_under_lock_stays_clean(tmp_path):
    (tmp_path / "helpers.py").write_text(textwrap.dedent(_SHARED_HELPERS))
    (tmp_path / "app.py").write_text(textwrap.dedent("""\
        import threading
        from helpers import enqueue

        class Shard:
            def __init__(self, server):
                self._mu = threading.Lock()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                with self._mu:
                    enqueue(req)
                return b""
    """))
    assert lint.run_lint([str(tmp_path)]) == []


def test_thread_local_state_exempt(tmp_path):
    (tmp_path / "app.py").write_text(textwrap.dedent("""\
        import threading

        class Shard:
            def __init__(self, server):
                self._local = threading.local()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                self._local.scratch = req
                return b""
    """))
    assert lint.run_lint([str(tmp_path)]) == []


def test_handler_registered_as_bare_function(tmp_path):
    (tmp_path / "app.py").write_text(textwrap.dedent("""\
        SEEN = []

        def handle(method, req):
            SEEN.append(req)
            return b""

        def setup(server):
            server.add_service("Ps", handle)
    """))
    findings = [f for f in lint.run_lint([str(tmp_path)])
                if f.check == "fiber-shared-state"]
    assert len(findings) == 1
    assert "SEEN" in findings[0].message


# ---- local-variable type inference (x = Class(); x.meth()) ----

def test_local_constructor_binding_resolves(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class Worker:
                def run(self):
                    pass
        """,
        app="""\
            from lib import Worker

            def main():
                w = Worker()
                w.run()
        """,
    )
    main = _only_node(g, ":main")
    assert _only_node(g, "Worker.run") in _callee_ids(g, main)


def test_local_binding_through_module_alias_and_ifexp(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class Worker:
                def run(self):
                    pass
        """,
        app="""\
            import lib

            def main(flag, given):
                w = lib.Worker() if flag else None
                v = given or lib.Worker()
                w.run()
                v.run()
        """,
    )
    main = _only_node(g, ":main")
    run = _only_node(g, "Worker.run")
    # both the conditional and the or-default bind to ONE class each
    assert [c for c in _callee_ids(g, main) if c == run] == [run]
    assert sum(1 for s in g.callees(main) if s.callee == run) == 2


def test_local_ambiguous_stays_deferred_but_call_results_resolve(tmp_path):
    # The PR-3 deferral's second half: calls on CALL RESULTS now resolve
    # through return-type inference — `y = factory(); y.run()` follows
    # the factory's direct in-package return.  Ambiguity rules are
    # unchanged: a local bound to two classes (or a factory whose returns
    # disagree) stays unresolved.
    g = _graph(
        tmp_path,
        lib="""\
            class A:
                def run(self):
                    pass

            class B:
                def run(self):
                    pass

            def factory():
                return A()

            def two_faced(flag):
                if flag:
                    return A()
                return B()
        """,
        app="""\
            from lib import A, B, factory, two_faced

            def ambiguous(flag):
                x = A()
                if flag:
                    x = B()
                x.run()

            def call_result():
                y = factory()
                y.run()

            def ambiguous_factory():
                z = two_faced(True)
                z.run()
        """,
    )
    run_a = _only_node(g, "A.run")
    run_b = _only_node(g, "B.run")
    amb = _callee_ids(g, _only_node(g, ":ambiguous"))
    assert run_a not in amb and run_b not in amb
    cr = _callee_ids(g, _only_node(g, ":call_result"))
    assert run_a in cr            # the closed deferral
    assert run_b not in cr
    assert _only_node(g, ":factory") in cr
    # a factory whose returns name two classes is ambiguous → no edge
    af = _callee_ids(g, _only_node(g, ":ambiguous_factory"))
    assert run_a not in af and run_b not in af


def test_nested_def_reads_enclosing_local_binding(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class Worker:
                def run(self):
                    pass
        """,
        app="""\
            from lib import Worker

            def outer():
                w = Worker()

                def inner():
                    w.run()
                return inner
        """,
    )
    inner = _only_node(g, "outer.inner")
    assert _only_node(g, "Worker.run") in _callee_ids(g, inner)


def test_annotated_attr_and_conditional_constructor_resolve(tmp_path):
    # self.<attr>: T = Class(...) if cond else None — the AnnAssign +
    # IfExp form the combiner uses; the attr-type map must see through
    # both or handler-reachable calls on the held object stay opaque.
    g = _graph(tmp_path, m="""\
        class Helper:
            def work(self):
                pass

        class Owner:
            def __init__(self, on):
                self.h: "Helper | None" = Helper() if on else None

            def entry(self):
                self.h.work()
    """)
    entry = _only_node(g, "Owner.entry")
    assert _only_node(g, "Helper.work") in _callee_ids(g, entry)


def test_synchronized_helper_method_not_flagged_as_container(tmp_path):
    # self.q.add() where q's class is in-package and add() locks
    # internally: the call RESOLVES, the interprocedural walk checks the
    # callee's body, and the raw-container mutator heuristic must not
    # double-report.  An UNLOCKED helper still yields a finding — inside
    # the helper, with the chain.
    good = """\
        import threading

        class Combiner:
            def __init__(self):
                self._mu = threading.Lock()
                self._q = []

            def add(self, item):
                with self._mu:
                    self._q.append(item)

        class Shard:
            def __init__(self, server):
                self.q = Combiner()
                server.add_service("Ps", self._handle)

            def _handle(self, method, req):
                self.q.add(req)
                return b""
    """
    src = textwrap.dedent(good)
    (tmp_path / "good.py").write_text(src)
    assert [f for f in lint.run_lint([str(tmp_path)])
            if f.check == "fiber-shared-state"] == []
    bad = src.replace("        with self._mu:\n"
                      "            self._q.append(item)",
                      "        self._q.append(item)")
    assert bad != src
    (tmp_path / "good.py").write_text(bad)
    findings = [f for f in lint.run_lint([str(tmp_path)])
                if f.check == "fiber-shared-state"]
    assert len(findings) == 1
    assert "Combiner.add" in findings[0].message


# ---- return-type inference (calls on CALL RESULTS resolve) ----

def test_cached_accessor_call_result_resolves(tmp_path):
    # the obs.recorder(name).record shape: the accessor returns a local
    # that is ALSO fed from a cache lookup, but every resolved return
    # names one class — annotation-free inference from the constructor
    # binding
    g = _graph(
        tmp_path,
        vars="""\
            class LatencyRecorder:
                def record(self, s):
                    pass
        """,
        obs="""\
            from vars import LatencyRecorder

            _cache = {}

            def recorder(name):
                rec = _cache.get(name)
                if rec is None:
                    rec = LatencyRecorder()
                    _cache[name] = rec
                return rec
        """,
        app="""\
            import obs

            def instrument(name, v):
                obs.recorder(name).record(v)
        """,
    )
    rec = _only_node(g, "LatencyRecorder.record")
    assert rec in _callee_ids(g, _only_node(g, ":instrument"))


def test_string_annotation_return_type_resolves(tmp_path):
    g = _graph(
        tmp_path,
        rpc="""\
            class Stream:
                def write(self, b):
                    pass
        """,
        client="""\
            from brpc_tpu import nothing  # noqa
            import rpc

            class Client:
                def _push_stream(self, s) -> "rpc.Stream":
                    return self._streams[s]

                def push(self, s, frame):
                    self._push_stream(s).write(frame)
        """,
    )
    write = _only_node(g, "Stream.write")
    assert write in _callee_ids(g, _only_node(g, "Client.push"))


def test_optional_annotation_unwraps(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class Thing:
                def go(self):
                    pass
        """,
        app="""\
            from typing import Optional

            from lib import Thing

            def maybe_thing(flag) -> Optional[Thing]:
                return Thing() if flag else None

            def use(flag):
                t = maybe_thing(flag)
                t.go()
        """,
    )
    go = _only_node(g, "Thing.go")
    assert go in _callee_ids(g, _only_node(g, ":use"))


def test_constructor_call_result_chain_resolves(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class W:
                def __init__(self):
                    pass

                def run(self):
                    pass
        """,
        app="""\
            from lib import W

            def inline():
                W().run()
        """,
    )
    assert _only_node(g, "W.run") in _callee_ids(g, _only_node(g, ":inline"))


def test_factory_typed_attr_assignment(tmp_path):
    # self.<attr> = make_channel() types the attr through the factory's
    # return type — held-object calls resolve
    g = _graph(
        tmp_path,
        lib="""\
            class Channel:
                def __init__(self, addr):
                    pass

                def call(self, m):
                    pass

            def make_channel(addr):
                return Channel(addr)
        """,
        app="""\
            from lib import make_channel

            class Client:
                def __init__(self, addr):
                    self.ch = make_channel(addr)

                def go(self):
                    self.ch.call("M")
        """,
    )
    call = _only_node(g, "Channel.call")
    assert call in _callee_ids(g, _only_node(g, "Client.go"))


def test_factory_return_chain_fixpoint(tmp_path):
    g = _graph(
        tmp_path,
        lib="""\
            class C:
                def m(self):
                    pass

            def inner():
                return C()

            def outer():
                return inner()
        """,
        app="""\
            from lib import outer

            def use():
                x = outer()
                x.m()
        """,
    )
    assert _only_node(g, "C.m") in _callee_ids(g, _only_node(g, ":use"))


# ---- may-throw fixpoint ----

def _throws(g, suffix):
    return g.throw_summary(_only_node(g, suffix))


def test_may_throw_explicit_raise_and_propagation(tmp_path):
    g = _graph(tmp_path, m="""\
        def boom():
            raise ValueError("bad")

        def mid():
            boom()

        def top():
            mid()

        def quiet():
            return 1 + 2
    """)
    for suffix in (":boom", ":mid", ":top"):
        s = _throws(g, suffix)
        assert s.may_throw, suffix
        assert s.types == ("ValueError",), suffix
        assert s.confidence == "high", suffix
    q = _throws(g, ":quiet")
    assert not q.may_throw and not q.external
    assert q.confidence == "none"


def test_may_throw_absorbed_by_base_class_handler(tmp_path):
    g = _graph(tmp_path, m="""\
        def boom():
            raise KeyError("k")

        def guarded():
            try:
                boom()
            except LookupError:
                return None

        def misguarded():
            try:
                boom()
            except OSError:
                return None
    """)
    # KeyError < LookupError: the guard absorbs the proven throw
    assert not _throws(g, ":guarded").may_throw
    # an unrelated clause absorbs nothing — the KeyError unwinds out
    s = _throws(g, ":misguarded")
    assert s.types == ("KeyError",) and s.confidence == "high"


def test_may_throw_external_call_is_low_confidence_only(tmp_path):
    g = _graph(tmp_path, m="""\
        import os

        def rm(path):
            os.remove(path)
    """)
    s = _throws(g, ":rm")
    # os.remove can obviously raise, but the analysis cannot prove a
    # chain — external bit only, NEVER a proven may-throw (findings
    # built on summaries stay free of unverifiable chains)
    assert not s.may_throw
    assert s.external
    assert s.confidence == "external"


def test_may_throw_assert_statement(tmp_path):
    g = _graph(tmp_path, m="""\
        def check(x):
            assert x > 0, "positive"
            return x
    """)
    s = _throws(g, ":check")
    assert s.types == ("AssertionError",)


def test_may_throw_unknown_type_absorbed_only_by_catch_all(tmp_path):
    g = _graph(tmp_path, m="""\
        def relay(e):
            raise e

        def narrow():
            try:
                relay(make())
            except ValueError:
                return None

        def wide():
            try:
                relay(make())
            except Exception:
                return None

        def make():
            return RuntimeError("x")
    """)
    assert _throws(g, ":relay").unknown
    # a named clause cannot prove it absorbs an unknown-typed throw
    assert _throws(g, ":narrow").unknown
    # only a catch-all absorbs it
    assert not _throws(g, ":wide").may_throw


def test_may_throw_in_package_exception_hierarchy(tmp_path):
    g = _graph(tmp_path, m="""\
        class FabricError(RuntimeError):
            pass

        class WireError(FabricError):
            pass

        def boom():
            raise WireError("frame")

        def guarded():
            try:
                boom()
            except FabricError:
                return None

        def misguarded():
            try:
                boom()
            except OSError:
                return None
    """)
    assert _throws(g, ":boom").types == ("WireError",)
    # the scanned ClassDef chain WireError -> FabricError is honoured
    assert not _throws(g, ":guarded").may_throw
    assert _throws(g, ":misguarded").types == ("WireError",)


def test_may_throw_recursive_cycle_terminates(tmp_path):
    g = _graph(tmp_path, m="""\
        def ping(n):
            if n <= 0:
                raise TimeoutError("spin")
            return pong(n - 1)

        def pong(n):
            return ping(n)
    """)
    assert _throws(g, ":ping").types == ("TimeoutError",)
    assert _throws(g, ":pong").types == ("TimeoutError",)


def test_may_throw_fixpoint_deterministic(tmp_path):
    src = """\
        class AppError(Exception):
            pass

        def a():
            raise AppError("a")

        def b():
            a()
            assert True

        def c(x):
            if x:
                raise ValueError(x)
            b()
    """
    g1 = _graph(tmp_path, m=src)
    other = tmp_path / "again"
    other.mkdir()
    g2 = _graph(other, m=src)
    t1 = {nid.split(":", 1)[-1]: g1.compute_throws()[nid]
          for nid in g1.nodes}
    t2 = {nid.split(":", 1)[-1]: g2.compute_throws()[nid]
          for nid in g2.nodes}
    assert t1 == t2
    # and re-computation on the same graph is cached + identical
    assert g1.compute_throws() is g1.compute_throws()
