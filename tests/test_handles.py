"""Dynamic handle ledger (brpc_tpu.analysis.handles): Python-side
bookkeeping of every owning brt_* handle with creation stacks, cross-
checked against the native ground-truth counters
(``brt_debug_handle_counts``) — and the proof that it catches the
ROADMAP stream-receiver leak (a stream client dying WITHOUT a graceful
close) before the socket-failure teardown clears it."""

import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.analysis import handles, race


@pytest.fixture(autouse=True)
def _ledger_isolation():
    handles.set_enabled(True)
    yield
    race.set_sample(None)
    handles.set_enabled(None)


# ---- ledger unit behavior (no native core needed) ----


def test_create_destroy_roundtrip_with_fake_handles():
    base = handles.live_counts().get("widget", 0)
    handles.note_create("widget", 0x1111)
    handles.note_create("widget", 0x2222)
    assert handles.live_counts().get("widget", 0) == base + 2
    recs = handles.live("widget")
    assert {r.handle for r in recs} >= {0x1111, 0x2222}
    assert any("test_create_destroy_roundtrip" in r.stack for r in recs)
    handles.note_destroy("widget", 0x1111)
    handles.note_destroy("widget", 0x2222)
    assert handles.live_counts().get("widget", 0) == base


def test_failed_constructor_and_unknown_destroy_are_tolerated():
    base = dict(handles.live_counts())
    handles.note_create("gizmo", 0)       # NULL: constructor failed
    handles.note_create("gizmo", None)    # ctypes NULL return
    assert handles.live_counts().get("gizmo", 0) == base.get("gizmo", 0)
    handles.note_destroy("gizmo", 0xdead)  # never created: no underflow
    assert handles.live_counts().get("gizmo", 0) == base.get("gizmo", 0)
    assert handles.stats()["gizmo"]["unknown_destroys"] >= 1


def test_sampling_reuses_racecheck_machinery():
    race.set_sample(1000)
    try:
        for i in range(5):
            handles.note_create("sampled", 0x9000 + i)
        recs = [r for r in handles.live("sampled")]
        # first creation of the kind is always captured; later ones
        # carry the placeholder (counts stay exact either way)
        stacks = [r.stack for r in sorted(recs, key=lambda r: r.seq)]
        assert handles.SAMPLED_OUT in stacks
        assert any(handles.SAMPLED_OUT not in s for s in stacks)
        assert handles.live_counts()["sampled"] == 5
    finally:
        for i in range(5):
            handles.note_destroy("sampled", 0x9000 + i)


def test_report_carries_kind_count_and_stack():
    handles.note_create("reported", 0x7777)
    try:
        text = handles.report()
        assert "reported=1" in text or "reported" in text
        assert "0x7777" in text
        assert "created here" in text
    finally:
        handles.note_destroy("reported", 0x7777)


def test_disabled_ledger_records_nothing():
    handles.set_enabled(False)
    handles.note_create("off", 0x1234)
    assert handles.live_counts().get("off", 0) == 0
    handles.set_enabled(True)


# ---- native cross-check: Python bookkeeping vs C++ ground truth ----


@pytest.mark.needs_native
def test_python_ledger_agrees_with_native_counts_across_lifecycle():
    if not rpc._lib or not isinstance(
            getattr(rpc._lib, "brt_server_new", None), rpc._LedgerFn):
        pytest.skip("ABI wrappers not installed "
                    "(BRPC_TPU_HANDLECHECK was off at load)")
    py0 = handles.live_counts()
    nat0 = rpc.debug_handle_counts()
    srv = rpc.Server()
    srv.add_service("Echo", lambda m, b: b)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    pc = ch.call_async("Echo", "M", b"x")
    group = rpc.CallGroup()
    group.add(pc)
    assert pc.join() == b"x"
    py1 = handles.live_counts()
    nat1 = rpc.debug_handle_counts()
    for kind in ("server", "channel", "call_group"):
        py_delta = py1.get(kind, 0) - py0.get(kind, 0)
        nat_delta = nat1[kind] - nat0.get(kind, 0)
        assert py_delta == nat_delta == 1, (kind, py_delta, nat_delta)
    # the joined call was destroyed on both sides
    assert py1.get("call", 0) == py0.get("call", 0)
    group.close()
    ch.close()
    srv.close()
    py2 = handles.live_counts()
    nat2 = rpc.debug_handle_counts()
    for kind in ("server", "channel", "call_group", "call"):
        assert py2.get(kind, 0) == py0.get(kind, 0), kind
        assert nat2[kind] == nat0.get(kind, 0), kind


@pytest.mark.needs_native
def test_leaked_pending_call_is_visible_then_reaped():
    srv = rpc.Server()
    srv.add_service("Echo", lambda m, b: b)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    before = handles.live_counts().get("call", 0)
    race.set_sample(1)
    pc = ch.call_async("Echo", "M", b"y")
    live = handles.live("call")
    assert handles.live_counts().get("call", 0) == before + 1
    assert any("call_async" in r.stack for r in live)
    pc.close()  # reap
    assert handles.live_counts().get("call", 0) == before
    ch.close()
    srv.close()


# ---- THE seeded leak: stream client dies without a graceful close ----


class _Recorder:
    def __init__(self):
        self.frames = []
        self.closed = False

    def on_data(self, data):
        self.frames.append(bytes(data))

    def on_closed(self):
        self.closed = True


def _settle(predicate, timeout_s=3.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


@pytest.mark.needs_native
def test_ledger_catches_stream_receiver_leak_and_teardown_clears_it():
    """The ROADMAP leak, end to end: a server-side stream receiver whose
    client vanishes without CLOSE is (1) visible in the dynamic ledger —
    nonzero live ``stream_receiver`` with a creation stack — with the
    native ``stream_relay`` ground truth agreeing, and (2) torn down to
    zero by the socket-failure hook once the dead connection fails
    (``on_closed`` fires, the registry entry frees, both ledgers return
    to baseline)."""
    race.set_sample(1)  # the leak report must carry a real stack
    recorder = _Recorder()
    srv = rpc.Server()

    def handler(method, request, accept):
        accept(recorder)
        return b"accepted"

    srv.add_stream_handler("T", handler)
    port = srv.start("127.0.0.1:0")
    addr = f"127.0.0.1:{port}"
    py0 = handles.live_counts().get("stream_receiver", 0)
    nat0 = rpc.debug_handle_counts().get("stream_relay", 0)

    ch = rpc.Channel(addr)
    st = ch.stream("T", "S")
    st.write(b"delta-1")
    assert _settle(lambda: recorder.frames == [b"delta-1"])

    # The client now ABANDONS the stream: no close, no abort — the
    # receiver is live on the server with nothing left to release it.
    # This is the leak; both ledgers must see it.
    assert handles.live_counts().get("stream_receiver", 0) == py0 + 1
    assert rpc.debug_handle_counts().get("stream_relay", 0) == nat0 + 1
    (leak,) = [r for r in handles.live("stream_receiver")
               if r.handle not in ()][-1:]
    assert "accept" in leak.stack  # creation stack points at the bind

    # "Client death": every connection to the server fails (what the
    # kernel delivers when the client process dies).  The socket-failure
    # teardown must fire on_closed and drain BOTH ledgers to baseline.
    assert rpc.debug_fail_connections(addr) >= 1
    assert _settle(lambda: handles.live_counts().get(
        "stream_receiver", 0) == py0), handles.report()
    assert _settle(lambda: rpc.debug_handle_counts().get(
        "stream_relay", 0) == nat0)
    assert recorder.closed  # the receiver was told, not just dropped

    # local client half: release bookkeeping, then teardown
    st.abort()
    assert _settle(
        lambda: rpc.debug_handle_counts().get("stream", 0) == 0)
    ch.close()
    srv.close()


@pytest.mark.needs_native
def test_graceful_close_never_trips_the_ledger():
    recorder = _Recorder()
    srv = rpc.Server()
    srv.add_stream_handler("T", lambda m, r, accept:
                           (accept(recorder), b"")[1])
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    py0 = handles.live_counts()
    st = ch.stream("T", "S")
    st.write(b"a")
    st.write(b"b")
    st.close()
    assert st.join(timeout_s=5.0)
    assert _settle(lambda: handles.live_counts().get(
        "stream_receiver", 0) == py0.get("stream_receiver", 0))
    assert recorder.frames == [b"a", b"b"] and recorder.closed
    ch.close()
    srv.close()


@pytest.mark.needs_native
def test_abort_over_healthy_socket_frees_the_peer_receiver():
    """In-process teardown: pooled SINGLE connections outlive the
    channel, so a plain abort used to strand the server receiver until
    process exit.  Abort now sends a best-effort CLOSE when the socket
    is healthy — the peer frees its receiver without a connection
    death."""
    recorder = _Recorder()
    srv = rpc.Server()
    srv.add_stream_handler("T", lambda m, r, accept:
                           (accept(recorder), b"")[1])
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    py0 = handles.live_counts().get("stream_receiver", 0)
    st = ch.stream("T", "S")
    st.write(b"x")
    st.abort()
    assert _settle(lambda: handles.live_counts().get(
        "stream_receiver", 0) == py0), handles.report()
    assert recorder.closed
    ch.close()
    srv.close()


# ---- exception-edge leaks: the unwinding path is what the ledger sees ----


class _FakeOwner:
    """A minimal owner the ledger tracks, shaped like the rpc wrappers:
    create on construction, destroy on close, context-managed."""

    def __init__(self, kind):
        self._kind = kind
        self._h = id(self) & 0xffffffff
        handles.note_create(kind, self._h)

    def close(self):
        if self._h:
            handles.note_destroy(self._kind, self._h)
            self._h = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def test_implicit_throw_between_create_and_close_leaks():
    """The exception-flow finding class, witnessed dynamically: a callee
    raise between create and close leaks the handle on the unwind —
    exactly what the static check flags at the throwing call site."""
    handles.clear()
    base = handles.live_counts().get("exc", 0)

    def parse(payload):
        raise ValueError("bad frame")

    def serve(payload):
        ch = _FakeOwner("exc")
        body = parse(payload)   # unwinds: ch.close() below never runs
        ch.close()
        return body

    with pytest.raises(ValueError):
        serve(b"x")
    assert handles.live_counts().get("exc", 0) == base + 1
    handles.clear()


def test_finally_and_with_cover_the_unwinding_edge():
    handles.clear()
    base = handles.live_counts().get("exc", 0)

    def parse(payload):
        raise ValueError("bad frame")

    def serve_finally(payload):
        ch = _FakeOwner("exc")
        try:
            return parse(payload)
        finally:
            ch.close()

    def serve_with(payload):
        with _FakeOwner("exc"):
            return parse(payload)

    for fn in (serve_finally, serve_with):
        with pytest.raises(ValueError):
            fn(b"x")
        assert handles.live_counts().get("exc", 0) == base
    handles.clear()


def test_handler_release_covers_only_its_own_try():
    """The scoped-trust rule, dynamically: the except clause's close
    runs only when ITS try raises — an exception after the try finds
    the handle live and leaks it, which is why the static check never
    lets a handler bless call sites outside its own try."""
    handles.clear()
    base = handles.live_counts().get("exc", 0)

    def parse(payload):
        raise ValueError("bad frame")

    def serve(payload):
        ch = _FakeOwner("exc")
        try:
            head = len(payload)
        except TypeError:
            ch.close()
            raise
        body = parse(payload)   # NOT covered by the handler above
        ch.close()
        return head, body

    with pytest.raises(ValueError):
        serve(b"x")
    assert handles.live_counts().get("exc", 0) == base + 1
    handles.clear()
