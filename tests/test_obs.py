"""brpc_tpu.obs: bvar-semantics reducers, windows over a fake clock,
latency percentile bounds, registry dumps, rpcz ring, and (native-gated)
the instrumented RPC fabric + the _status builtin service."""

import json
import threading

import numpy as np
import pytest

from brpc_tpu import obs
from brpc_tpu.obs import rpcz, status_service
from brpc_tpu.obs.vars import (
    Adder,
    LatencyRecorder,
    Maxer,
    Miner,
    PassiveStatus,
    PerSecond,
    Registry,
    Window,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# reducers
# ---------------------------------------------------------------------------

def test_adder_semantics():
    a = Adder()
    assert a.get_value() == 0
    a.add()
    a.add(4)
    a << 5
    assert a.get_value() == 10
    a.add(-3)
    assert a.get_value() == 7
    a.reset()
    assert a.get_value() == 0


def test_maxer_miner_semantics():
    mx, mn = Maxer(), Miner()
    assert mx.get_value() == 0  # empty -> 0, like bvar's default dump
    assert mn.get_value() == 0
    for v in (3, 9, 1):
        mx.update(v)
        mn.update(v)
    assert mx.get_value() == 9
    assert mn.get_value() == 1


def test_adder_across_threads():
    a = Adder()
    n_threads, per = 8, 10_000

    def work():
        for _ in range(per):
            a.add(1)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert a.get_value() == n_threads * per


def test_passive_status():
    box = {"v": 3}
    p = PassiveStatus(lambda: box["v"])
    assert p.get_value() == 3
    box["v"] = 7
    assert p.get_value() == 7


# ---------------------------------------------------------------------------
# windows on a fake clock
# ---------------------------------------------------------------------------

def test_window_over_adder_fake_clock():
    clk = FakeClock()
    a = Adder()
    w = Window(a, window_size=3, clock=clk)
    for _ in range(5):       # 5 seconds, 10 units each
        a.add(10)
        clk.advance(1.0)
        w.get_value()        # lazy sampler: reads drive the per-second ticks
    # window covers the last 3 seconds: 30 units
    assert w.get_value() == 30
    clk.advance(10.0)        # quiet gap longer than the window
    assert w.get_value() == 0


def test_window_over_maxer_fake_clock():
    clk = FakeClock()
    m = Maxer()
    w = Window(m, window_size=3, clock=clk)
    m.update(100)            # second 0
    clk.advance(1.0)
    w.get_value()            # tick so the sample lands in its own slot
    m.update(7)              # second 1
    clk.advance(1.0)
    w.get_value()
    m.update(5)              # second 2
    clk.advance(1.0)
    assert w.get_value() == 100
    clk.advance(1.0)         # second 0's max ages out of the 3s window
    assert w.get_value() == 7
    clk.advance(2.0)         # everything ages out
    assert w.get_value() == 0


def test_per_second_fake_clock():
    clk = FakeClock()
    a = Adder()
    qps = PerSecond(a, window_size=10, clock=clk)
    for _ in range(10):      # 50 events/s for 10 seconds
        a.add(50)
        clk.advance(1.0)
    assert qps.get_value() == pytest.approx(50.0)
    for _ in range(10):      # rate drops to 0
        clk.advance(1.0)
        qps.get_value()
    assert qps.get_value() == pytest.approx(0.0)


def test_per_second_rejects_maxer():
    with pytest.raises(TypeError):
        PerSecond(Maxer(), clock=FakeClock()).get_value()


# ---------------------------------------------------------------------------
# latency recorder
# ---------------------------------------------------------------------------

def test_latency_recorder_percentile_bounds():
    rec = LatencyRecorder(clock=FakeClock())
    rng = np.random.default_rng(0)
    # lognormal latencies around 1ms
    samples_s = np.exp(rng.normal(np.log(1e-3), 1.0, 20_000))
    for s in samples_s:
        rec.record(float(s))
    assert rec.count == 20_000
    true_us = np.sort(samples_s * 1e6)
    # log-bucket quantisation: 20 buckets/decade -> ±12.2% relative error,
    # allow 2 bucket widths for rank-vs-midpoint slop
    for q in (0.50, 0.90, 0.99, 0.999):
        got = rec.percentile(q)
        want = float(true_us[min(int(q * 20_000), 19_999)])
        assert want / 1.3 <= got <= want * 1.3, (q, got, want)
    assert rec.avg_us == pytest.approx(float(np.mean(true_us)), rel=0.01)
    assert rec.max_us == pytest.approx(float(true_us[-1]), rel=0.01)


def test_latency_recorder_value_shape():
    rec = LatencyRecorder(clock=FakeClock())
    rec.record(0.001)
    v = rec.get_value()
    assert v["count"] == 1
    assert set(v) == {"count", "qps", "avg_us", "max_us", "p50_us",
                      "p90_us", "p99_us", "p999_us"}
    assert 800 < v["p50_us"] < 1250


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_dump_and_filtering():
    reg = Registry()
    a = Adder()
    a.add(42)
    reg.expose("rpc_client_echo_count", a)
    reg.expose("ps_server_keys", Adder())
    text = reg.dump_exposed()
    assert "rpc_client_echo_count : 42" in text
    assert "ps_server_keys : 0" in text
    # substring, glob, predicate filters
    assert "ps_server" not in reg.dump_exposed("rpc_")
    assert list(reg.dump_exposed_dict("rpc_*")) == ["rpc_client_echo_count"]
    assert reg.dump_exposed_dict(lambda n: n.startswith("ps_")) == {
        "ps_server_keys": 0}
    reg.hide("ps_server_keys")
    assert "ps_server_keys" not in reg.names()


def test_expose_default_registry():
    a = Adder()
    a.expose("test_obs_tmp_var")
    try:
        assert "test_obs_tmp_var" in obs.dump_exposed("test_obs_tmp_")
    finally:
        obs.default_registry().hide("test_obs_tmp_var")


# ---------------------------------------------------------------------------
# rpcz
# ---------------------------------------------------------------------------

def test_rpcz_ring_bounded():
    ring = rpcz.SpanRing(capacity=16)
    for i in range(100):
        ring.append(rpcz.Span("S", f"m{i}"))
    assert len(ring) == 16
    dumped = ring.dump(limit=100)
    assert len(dumped) == 16
    # newest first, oldest 84 fell off
    assert dumped[0]["method"] == "m99"
    assert dumped[-1]["method"] == "m84"
    ring.set_capacity(4)
    assert len(ring) == 4


def test_rpcz_dump_filters():
    ring = rpcz.SpanRing(capacity=64)
    ring.append(rpcz.Span("Echo", "Echo", side="client"))
    ring.append(rpcz.Span("Echo", "Echo", side="server"))
    ring.append(rpcz.Span("Ps", "Lookup", side="client", error_code=2001,
                          error_text="boom"))
    assert len(ring.dump(service="Echo")) == 2
    assert len(ring.dump(side="server")) == 1
    assert len(ring.dump(errors_only=True)) == 1
    assert len(ring.dump(limit=1)) == 1
    assert ring.dump(method="Lookup")[0]["error_text"] == "boom"


def test_span_context_manager_records_and_reraises():
    ring = rpcz.SpanRing(capacity=8)
    with rpcz.span("User", "ok", ring=ring) as sp:
        sp.annotate("phase1")
    with pytest.raises(ValueError):
        with rpcz.span("User", "bad", ring=ring):
            raise ValueError("nope")
    spans = ring.dump()
    assert [d["method"] for d in spans] == ["bad", "ok"]
    assert spans[0]["error_code"] == 2001 and "nope" in spans[0]["error_text"]
    assert spans[1]["annotations"] == ["phase1"]
    assert spans[1]["latency_us"] >= 0


def test_status_handler_without_rpc():
    """The _status handler is just a function — exercises the full wire
    mapping with no native server."""
    reg = Registry()
    counter = Adder()
    counter.add(5)
    reg.expose("demo_counter", counter)
    ring = rpcz.SpanRing(capacity=8)
    ring.append(rpcz.Span("Echo", "Echo", side="server"))
    h = status_service.make_status_handler(registry=reg, ring=ring)
    assert h("health", b"") == b"ok"
    assert h("vars", b"") == b"demo_counter : 5"
    assert json.loads(h("vars_json", b"")) == {"demo_counter": 5}
    spans = json.loads(h("rpcz", json.dumps({"limit": 10}).encode()))
    assert spans[0]["service"] == "Echo"
    assert b"Echo.Echo" in h("rpcz_text", b"")
    with pytest.raises(ValueError):
        h("rpcz", b'{"bogus": 1}')
    with pytest.raises(ValueError):
        h("nope", b"")


def test_disabled_gate():
    obs.set_enabled(False)
    try:
        assert not obs.enabled()
    finally:
        obs.set_enabled(True)
    assert obs.enabled()


# ---------------------------------------------------------------------------
# the instrumented fabric (needs the native core)
# ---------------------------------------------------------------------------

@pytest.mark.needs_native
def test_channel_call_records_spans_and_latency():
    from brpc_tpu import rpc

    obs.reset_fabric_vars()
    rpcz.clear()
    srv = rpc.Server()

    def echo(method, req):
        if method != "Echo":
            # unknown methods must FAIL (the error-span assertions below
            # drive the Boom call through the failure path)
            raise ValueError(f"no method {method}")
        return req

    srv.add_service("Echo", echo)
    srv.add_status_service()
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    try:
        for _ in range(3):
            assert ch.call("Echo", "Echo", b"x" * 100) == b"x" * 100

        # matching client/server recorders with the same call count
        dump = obs.dump_exposed_dict("rpc_")
        assert dump["rpc_client_Echo_Echo"]["count"] == 3
        assert dump["rpc_server_Echo_Echo"]["count"] == 3
        assert dump["rpc_client_Echo_Echo"]["avg_us"] > 0
        assert obs.counter("rpc_client_out_bytes").get_value() == 300
        assert obs.counter("rpc_server_in_bytes").get_value() == 300

        # matching client/server spans for the same call
        client = obs.dump_rpcz(service="Echo", side="client")
        server = obs.dump_rpcz(service="Echo", side="server")
        assert len(client) == 3 and len(server) == 3
        assert client[0]["request_bytes"] == server[0]["request_bytes"] == 100
        assert client[0]["peer"] == f"127.0.0.1:{port}"
        # server time is contained in client time
        assert server[0]["latency_us"] <= client[0]["latency_us"]

        # the _status builtin serves both dumps over the fabric itself
        text = status_service.scrape_vars(ch, "rpc_client_Echo")
        assert "rpc_client_Echo_Echo : count=3" in text
        remote_spans = status_service.scrape_rpcz(ch, service="Echo",
                                                  side="server")
        assert len(remote_spans) == 3

        # failed calls carry the error through spans + error counters
        with pytest.raises(rpc.RpcError):
            ch.call("Echo", "Boom", b"")
        errs = obs.dump_rpcz(errors_only=True)
        assert any(d["side"] == "client" and d["method"] == "Boom"
                   for d in errs)
        assert any(d["side"] == "server" and d["method"] == "Boom"
                   for d in errs)
        assert obs.counter("rpc_client_errors").get_value() == 1
        assert obs.counter("rpc_server_errors").get_value() == 1
    finally:
        ch.close()
        srv.close()


@pytest.mark.needs_native
def test_ps_path_records_counters():
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

    obs.reset_fabric_vars()
    rpcz.clear()
    vocab, dim, shards = 32, 8, 2
    servers = [PsShardServer(vocab, dim, i, shards) for i in range(shards)]
    emb = RemoteEmbedding([s.address for s in servers], vocab, dim)
    try:
        ids = np.array([0, 5, 17, 31], np.int32)
        rows = emb.lookup(ids)
        assert rows.shape == (4, dim)
        emb.apply_gradients(ids, np.ones((4, dim), np.float32))

        assert obs.counter("ps_client_lookup_keys").get_value() == 4
        assert obs.counter("ps_client_apply_keys").get_value() == 4
        assert obs.counter("ps_server_keys").get_value() == 8  # both ops
        assert obs.counter("ps_server_bytes_out").get_value() > 0
        assert obs.recorder("ps_client_lookup").count == 1
        # per-shard recorders saw one Lookup + one ApplyGrad each
        dump = obs.dump_exposed_dict("ps_server_shard")
        assert dump["ps_server_shard0_Lookup"]["count"] == 1
        # apply_gradients rides the idempotent unary write method
        assert dump["ps_server_shard1_ApplyGradId"]["count"] == 1
        # dump_exposed shows live ps_* lines after the instrumented path
        assert "ps_client_lookup" in obs.dump_exposed("ps_")
    finally:
        emb.close()
        for s in servers:
            s.close()


def test_collective_channel_counters():
    import jax
    import jax.numpy as jnp

    from brpc_tpu.parallel import CollectiveChannel, make_mesh

    obs.reset_fabric_vars()
    mesh = make_mesh({"dp": 8})
    chan = CollectiveChannel(mesh, "dp")
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    jax.jit(chan.all_reduce)(x)
    assert obs.counter("collective_all_reduce_calls").get_value() == 1
    assert obs.counter("collective_all_reduce_bytes").get_value() == 64 * 4
    chan.all_gather(x)
    assert obs.counter("collective_all_gather_calls").get_value() == 1


def test_maxer_helper_cached_exposed_and_reset():
    obs.reset_fabric_vars()
    m = obs.maxer("test_high_water")
    assert obs.maxer("test_high_water") is m  # cached per name
    m.update(3)
    m.update(7)
    m.update(5)
    assert m.get_value() == 7
    assert "test_high_water" in obs.dump_exposed_dict()
    obs.reset_fabric_vars()
    assert "test_high_water" not in obs.dump_exposed_dict()
