"""Cross-language contract tier (``brpc_tpu.analysis.native``).

Extraction units run over the REAL ``cpp/capi`` translation units — the
tokenizer, the brace-matching function extractor, and the wire
read-sequence extraction are exercised against the code they gate, not
just synthetic strings.  Seeded fixtures then prove detector power:
wrong-width and wrong-order native parsers, stale ``native_sites``
declarations, undeclared parsers, counts used as bounds before
validation, undeclared/unsanctioned error codes, and ledger bumps
leaked on native error paths must all be flagged — and the width-drift
fixture is ALSO caught at runtime by the fuzzer's parity harness
(static/dynamic parity).  CLI wiring, exit codes, and the baseline
roundtrip close the loop.
"""

import json
import os
import struct
import textwrap
import types

import pytest

from brpc_tpu import wire
from brpc_tpu.analysis import fuzz, lint, native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI = os.path.join(REPO, "cpp", "capi")
PKG = os.path.join(REPO, "brpc_tpu")


def _fn(path, qual):
    with open(path, "r", encoding="utf-8") as f:
        fns = native.extract_functions(f.read(), path)
    hits = [fn for fn in fns if fn.qual == qual]
    assert hits, f"{qual} not extracted from {path}: " \
                 f"{sorted(f.qual for f in fns)}"
    return hits[0]


def _fixture_tree(tmp_path, cc_source, errors_h="enum RpcError "
                                                "{ EREQUEST = 1003 };"):
    (tmp_path / "cpp" / "capi").mkdir(parents=True)
    (tmp_path / "cpp" / "rpc").mkdir(parents=True)
    cc = tmp_path / "cpp" / "capi" / "fix.cc"
    cc.write_text(textwrap.dedent(cc_source))
    (tmp_path / "cpp" / "rpc" / "errors.h").write_text(errors_h)
    return str(cc), str(tmp_path)


def _schema_for(fields, site="cpp/capi/fix.cc:ServeFix"):
    sch = wire.FrameSchema(name="fix_req", fields=tuple(fields),
                           native_sites=(site,))
    return types.SimpleNamespace(REGISTRY={"fix_req": sch})


# ---------------------------------------------------------------------------
# tokenizer + extractor over the real TUs
# ---------------------------------------------------------------------------

def test_strip_preserves_length_and_lines():
    src = ('int f() {\n'
           '  const char* s = "}{ not a brace";  // } neither\n'
           '  /* } multi\n'
           '     line } */\n'
           '#define X }\n'
           '  return 0;\n'
           '}\n')
    out = native.strip_comments_and_strings(src)
    assert len(out) == len(src)
    assert out.count("\n") == src.count("\n")
    # exactly the real function braces survive
    assert out.count("{") == 1 and out.count("}") == 1


def test_extractor_finds_real_capi_functions():
    sl = _fn(os.path.join(CAPI, "ps_shard.cc"),
             "CPsService::ServeLookup")
    assert sl.buffer_params() == ["request"]
    # extern "C" ABI additions are seen too
    _fn(os.path.join(CAPI, "ps_shard.cc"), "brt_ps_shard_lookup_stats")
    # a constructor with a ctor-init-list head and the matching dtor
    stream = os.path.join(CAPI, "stream_capi.cc")
    ctor = _fn(stream, "CStreamRelay::CStreamRelay")
    assert "handle_inc" in ctor.body
    dtor = _fn(stream, "CStreamRelay::~CStreamRelay")
    assert "handle_dec" in dtor.body


def test_serve_lookup_read_sequence_extracted():
    sl = _fn(os.path.join(CAPI, "ps_shard.cc"),
             "CPsService::ServeLookup")
    events = native.wire_reads_of(sl)
    scalars = [e for e in events if e.kind == "scalar"]
    arrays = [e for e in events if e.kind == "array"]
    # count(i32) ++ [magic-peel: deadline i64] ++ count(i32) ++ ids tail
    assert [e.width for e in scalars] == [4, 8, 4]
    assert scalars[0].offset == 0
    assert len(arrays) == 1 and "count" in arrays[0].count_vars
    # the count reaches its bounds check BEFORE it drives the read
    guards = native.guarded_idents_of(sl)
    assert guards["count"] < arrays[0].line


def test_every_native_twin_schema_matched_in_tree():
    """The acceptance gate: every wire.REGISTRY schema with a declared
    C++ parse twin resolves against the real native tree and matches
    field-for-field — zero findings, zero pragmas."""
    twins = [s for s in wire.REGISTRY.values() if s.native_sites]
    assert twins, "registry lost its native twins"
    files = native.default_cpp_files(REPO)
    assert files, "cpp/capi tree missing"
    assert native.run_native_checks(files, REPO) == []


# ---------------------------------------------------------------------------
# detector power: seeded native drift (satellite fixtures)
# ---------------------------------------------------------------------------

#: wrong WIDTH: the schema says the count is i32, the seeded parser
#: reads i64 — exactly the silent-ABI-skew class the tier exists for
_WRONG_WIDTH_CC = """
    #include "x.h"
    namespace {
    void ServeFix(brt::IOBuf& request, brt::IOBuf* out) {
      int64_t count = 0;
      request.copy_to(&count, 8);
      if (count < 0 || request.size() != 8 + size_t(count) * 4) return;
      std::vector<int32_t> ids(size_t(count));
      request.copy_to(ids.data(), size_t(count) * 4, 8);
    }
    }
"""

#: wrong ORDER: schema declares (q, i), parser reads (i, q)
_WRONG_ORDER_CC = """
    #include "x.h"
    namespace {
    void ServeFix(brt::IOBuf& request, brt::IOBuf* out) {
      int32_t gen = 0;
      int64_t epoch = 0;
      request.copy_to(&gen, 4);
      request.copy_to(&epoch, 8, 4);
    }
    }
"""


def test_seeded_wrong_width_parser_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, _WRONG_WIDTH_CC)
    wm = _schema_for([wire.Int("count", "<i"),
                      wire.Array("ids", "<i4", "count")])
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    assert any(f.check == "wire-contract-native"
               and "width/order drift" in f.message for f in fs), \
        [f.message for f in fs]


def test_seeded_wrong_order_parser_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, _WRONG_ORDER_CC)
    wm = _schema_for([wire.Int("epoch", "<q"), wire.Int("gen", "<i")])
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    assert any("width/order drift" in f.message for f in fs)


def test_fuzzer_catches_the_same_width_drift_at_runtime():
    """Static/dynamic parity: a Python twin of the seeded wrong-width
    native parser fails ``parity_fuzz`` on schema-valid frames — the
    drift the native lint flags statically is exactly what the fuzz
    harness rejects dynamically."""
    sch = wire.FrameSchema(
        name="fix_req",
        fields=(wire.Int("count", "<i"),
                wire.Array("ids", "<i4", "count")))

    def drifted_unpack(payload):
        # the C++ fixture's behavior: reads an i64 count off an i32 frame
        (count,) = struct.unpack_from("<q", payload, 0)
        if count < 0 or len(payload) != 8 + count * 4:
            raise ValueError("bad frame")
        return count

    def good_pack(values):
        import numpy as np
        ids = np.asarray(values["ids"], np.int32)
        return struct.pack("<i", ids.size) + ids.tobytes()

    failures = fuzz.parity_fuzz(sch, good_pack, drifted_unpack,
                                seed=7, iters=20)
    assert failures and all(f.kind == "contract" for f in failures)
    # the faithful i32 parser passes the same harness
    def good_unpack(payload):
        (count,) = struct.unpack_from("<i", payload, 0)
        if count < 0 or len(payload) != 4 + count * 4:
            raise ValueError("bad frame")
        return count

    assert fuzz.parity_fuzz(sch, good_pack, good_unpack,
                            seed=7, iters=20) == []


def test_stale_native_site_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, _WRONG_WIDTH_CC)
    wm = _schema_for([wire.Int("count", "<i")],
                     site="cpp/capi/fix.cc:ServeGone")
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    stale = [f for f in fs if "registry is stale" in f.message]
    assert stale and stale[0].path == "brpc_tpu/wire.py"


def test_undeclared_native_parser_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        namespace {
        void SneakyParse(brt::IOBuf& request) {
          int32_t gen = 0;
          request.copy_to(&gen, 4);
        }
        }
    """)
    wm = types.SimpleNamespace(REGISTRY={})
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    assert any("no wire.REGISTRY schema claims it" in f.message
               for f in fs)


def test_count_used_as_bound_before_validation_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        namespace {
        void ServeFix(brt::IOBuf& request, brt::IOBuf* out) {
          int32_t count = 0;
          request.copy_to(&count, 4);
          std::vector<int32_t> ids(size_t(count));
          request.copy_to(ids.data(), size_t(count) * 4, 4);
        }
        }
    """)
    wm = _schema_for([wire.Int("count", "<i"),
                      wire.Array("ids", "<i4", "count")])
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    assert any("before validation" in f.message for f in fs)


# ---------------------------------------------------------------------------
# native-errors
# ---------------------------------------------------------------------------

def test_undeclared_error_code_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        void fail_it(Controller* cntl) {
          cntl->SetFailed(EMYSTERY, "nope");
        }
    """)
    fs = native.run_native_checks(
        [cc], root, checks=["native-errors"],
        wire_mod=types.SimpleNamespace(REGISTRY={}), sanctioned={1003})
    assert any(f.check == "native-errors"
               and "EMYSTERY" in f.message for f in fs)


def test_errno_namespace_resolves_clean(tmp_path):
    # the sub-1000 code space reuses POSIX errno — ECONNRESET is legal
    # outside serve paths (brt_debug_fail_connections uses it in-tree)
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        void fail_it(Controller* cntl) {
          cntl->SetFailed(ECONNRESET, "injected");
        }
    """)
    fs = native.run_native_checks(
        [cc], root, checks=["native-errors"],
        wire_mod=types.SimpleNamespace(REGISTRY={}), sanctioned={1003})
    assert fs == []


def test_unsanctioned_serve_path_code_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        namespace {
        void ServeFix(brt::IOBuf& request, Controller* cntl) {
          int32_t count = 0;
          request.copy_to(&count, 4);
          cntl->SetFailed(ELOGOFF, "drained");
        }
        }
    """, errors_h="enum RpcError { EREQUEST = 1003, ELOGOFF = 2003 };")
    wm = _schema_for([wire.Int("count", "<i")])
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    parity = [f for f in fs if f.check == "native-errors"]
    assert parity and "sanctioned" in parity[0].message
    assert "static/dynamic parity" in parity[0].message


# ---------------------------------------------------------------------------
# native-handle-balance
# ---------------------------------------------------------------------------

def test_handle_inc_leaked_on_error_return_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        void* leaky_new() {
          brt_capi::handle_inc(brt_capi::HandleKind::kServer);
          if (!init()) {
            return nullptr;
          }
          return ptr;
        }
    """)
    fs = native.run_native_checks(
        [cc], root, checks=["native-handle-balance"],
        wire_mod=types.SimpleNamespace(REGISTRY={}))
    assert len(fs) == 1
    assert "handle_inc(kServer)" in fs[0].message
    assert "error path" in fs[0].message


def test_handle_inc_balanced_on_error_path_clean(tmp_path):
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        void* careful_new() {
          brt_capi::handle_inc(brt_capi::HandleKind::kServer);
          if (!init()) {
            brt_capi::handle_dec(brt_capi::HandleKind::kServer);
            return nullptr;
          }
          return ptr;
        }
    """)
    fs = native.run_native_checks(
        [cc], root, checks=["native-handle-balance"],
        wire_mod=types.SimpleNamespace(REGISTRY={}))
    assert fs == []


def test_handle_inc_then_success_return_clean(tmp_path):
    # the in-tree idiom: inc immediately before the success return
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        void* ok_new() {
          auto* s = make();
          if (s == nullptr) {
            return nullptr;
          }
          brt_capi::handle_inc(brt_capi::HandleKind::kServer);
          return s;
        }
    """)
    fs = native.run_native_checks(
        [cc], root, checks=["native-handle-balance"],
        wire_mod=types.SimpleNamespace(REGISTRY={}))
    assert fs == []


# ---------------------------------------------------------------------------
# CLI wiring, exit codes, baseline roundtrip
# ---------------------------------------------------------------------------

def test_cli_native_checks_run_clean_in_tree(capsys):
    rc = lint.main(["--check", "wire-contract-native",
                    "--check", "native-errors",
                    "--check", "native-handle-balance", PKG])
    assert rc == 0
    assert "clean" in capsys.readouterr().err


def test_cli_unknown_check_exits_2_and_lists_native_names(capsys):
    with pytest.raises(SystemExit) as exc:
        lint.main(["--check", "bogus", PKG])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    for name in native.NATIVE_CHECKS:
        assert name in err


def test_native_checks_skip_outside_package_scans(tmp_path):
    # a tmp fixture tree has no brpc_tpu/ in its scan path: the native
    # tier must skip cleanly instead of linting the wrong repo's cpp/
    (tmp_path / "mod.py").write_text("x = 1\n")
    fs = lint.run_lint([str(tmp_path)],
                       checks=["wire-contract-native"])
    assert fs == []


def test_native_finding_baseline_roundtrip(tmp_path):
    cc, root = _fixture_tree(tmp_path, _WRONG_WIDTH_CC)
    wm = _schema_for([wire.Int("count", "<i"),
                      wire.Array("ids", "<i4", "count")])
    fs = native.run_native_checks([cc], root, wire_mod=wm,
                                  sanctioned={1003})
    assert fs
    # ids are stable: same inputs, same ids
    again = native.run_native_checks([cc], root, wire_mod=wm,
                                     sanctioned={1003})
    assert [f.id for f in fs] == [f.id for f in again]
    # cpp paths anchor machine-independently in the id hash
    assert lint._stable_path(fs[0].path).startswith("cpp/")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"ids": [f.id for f in fs]}))
    new, suppressed = lint.apply_baseline(
        fs, lint.load_baseline(str(baseline)))
    assert new == [] and len(suppressed) == len(fs)


# ---------------------------------------------------------------------------
# native-endian: byte order must be proven by the parity fuzzer
# ---------------------------------------------------------------------------

#: a FAITHFUL claimed parser (matches its schema field-for-field) whose
#: multi-byte reads still need a runtime endianness witness
_ENDIAN_CC = """
    #include "x.h"
    namespace {
    void ServeFix(brt::IOBuf& request, brt::IOBuf* out) {
      int32_t count = 0;
      request.copy_to(&count, 4);
      if (count < 0 || request.size() != 4 + size_t(count) * 4) return;
      std::vector<int32_t> ids(size_t(count));
      request.copy_to(ids.data(), size_t(count) * 4, 4);
    }
    }
"""


def test_native_endian_uncovered_schema_flagged(tmp_path):
    cc, root = _fixture_tree(tmp_path, _ENDIAN_CC)
    wm = _schema_for([wire.Int("count", "<i"),
                      wire.Array("ids", "<i4", "count")])
    fs = native.run_native_checks([cc], root, checks=["native-endian"],
                                  wire_mod=wm, covers={})
    assert len(fs) == 1, [f.message for f in fs]
    f = fs[0]
    assert f.check == "native-endian"
    assert "fix_req" in f.message and "byte order" in f.message
    assert "coverage_map" in f.message


def test_native_endian_covered_schema_clean(tmp_path):
    cc, root = _fixture_tree(tmp_path, _ENDIAN_CC)
    wm = _schema_for([wire.Int("count", "<i"),
                      wire.Array("ids", "<i4", "count")])
    fs = native.run_native_checks(
        [cc], root, checks=["native-endian"], wire_mod=wm,
        covers={"fix_target": ("fix_req",)})
    assert fs == [], [f.message for f in fs]


def test_native_endian_single_byte_reads_exempt(tmp_path):
    # one-byte fields have no byte order: nothing to prove
    cc, root = _fixture_tree(tmp_path, """
        #include "x.h"
        namespace {
        void ServeFix(brt::IOBuf& request, brt::IOBuf* out) {
          uint8_t tag = 0;
          request.copy_to(&tag, 1);
        }
        }
    """)
    wm = _schema_for([wire.Int("tag", "<b")])
    fs = native.run_native_checks([cc], root, checks=["native-endian"],
                                  wire_mod=wm, covers={})
    assert fs == [], [f.message for f in fs]


def test_native_endian_in_tree_every_twin_is_fuzz_covered():
    """The acceptance gate for the sub-check: every claimed native
    parser in the REAL tree is already covered by a parity-fuzz target
    — the default coverage map closes the loop with zero findings."""
    files = native.default_cpp_files(REPO)
    assert files
    covered = set()
    for names in fuzz.coverage_map().values():
        covered.update(names)
    claimed = {s.name for s in wire.REGISTRY.values() if s.native_sites}
    assert claimed <= covered, claimed - covered
    fs = native.run_native_checks(files, REPO, checks=["native-endian"])
    assert fs == [], [f.message for f in fs]
