"""Durable fabric: per-shard checkpoint/restore + snapshot-hydrated
provisioning (ISSUE 16).

The store half runs everywhere (tier-1): on-disk frame parsers
(``ckpt_snap`` / ``ckpt_delta`` / ``ckpt_marker``) reject torn,
truncated and bit-flipped files with a clean ``WireError``; the
:class:`CheckpointStore` write/restore cycle is proven with an
EXACT-arithmetic ledger (manual numpy replay of the teed bodies), and
every crash-mid-checkpoint shape — mid-snapshot, mid-append,
mid-compaction — lands restore on the last complete record, never a
byte more or less.

The server half (native-gated) closes the loop end to end: the live
apply path tees into the store, a cold restart replays to the exact
acked generation through the server's own arithmetic, and new
replicas / split destinations hydrate from the snapshot + delta tail
instead of a wholesale Sync off the live source.
"""

import os
import struct
import time

import numpy as np
import pytest

from brpc_tpu import durable, fault, obs, rpc, wire
from brpc_tpu.durable import (CheckpointStore, _pack_delta, _pack_marker,
                              _pack_snapshot, _unpack_delta,
                              _unpack_marker, _unpack_snapshot)
from brpc_tpu.ps_remote import (_pack_apply_req, _pack_windows,
                                _unpack_apply)

ROWS, DIM = 16, 4


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)
    fault.clear()


def _table(seed=0):
    rng = np.random.default_rng(seed)
    # exactly-representable values so replay comparisons are bit-exact
    return (rng.integers(-64, 64, (ROWS, DIM)).astype(np.float32)
            * np.float32(0.25))


def _body(ids, step, windows=None):
    """One verbatim replica_apply_body: dedup windows ++ apply_req with
    an exactly-representable per-step gradient (2**-step)."""
    ids = np.asarray(ids, np.int32)
    grads = np.full((ids.size, DIM), 2.0 ** -step, np.float32)
    return (_pack_windows(windows or {})
            + bytes(_pack_apply_req(ids, grads))), ids, grads


def _store_with_tail(root, nsteps=5, seed=0, **kw):
    """Base at gen 0 plus ``nsteps`` teed deltas; returns the store and
    the EXACT expected table after replaying every delta."""
    st = CheckpointStore(str(root), **kw)
    base = _table(seed)
    st.save_snapshot(7, 0, base, {"w": 3})
    expect = base.copy()
    for g in range(1, nsteps + 1):
        body, ids, grads = _body([g % ROWS, (g + 3) % ROWS], g,
                                 windows={"w": 3 + g})
        assert st.append_delta(g, body)
        np.subtract.at(expect, ids, grads)
    return st, base, expect


def _replay(point):
    """Manual replay of a RestorePoint through the same parse +
    arithmetic the server uses (lr folded at 1.0)."""
    out = point.table.copy()
    for _gen, body in point.deltas:
        _windows, off = durable._unpack_windows(body)
        ids, grads = _unpack_apply(memoryview(body)[off:], 0, ROWS, DIM)
        if ids.size:
            np.subtract.at(out, ids, grads)
    return out


# ---------------------------------------------------------------------------
# on-disk frame parsers: roundtrip + clean rejection
# ---------------------------------------------------------------------------

def test_snapshot_roundtrip_exact():
    tbl = _table(3)
    payload = _pack_snapshot(9, 42, tbl, {"writer-a": 5, "writer-b": 11})
    epoch, gen, out, windows, seeded = _unpack_snapshot(payload)
    assert (epoch, gen) == (9, 42)
    assert np.array_equal(out, tbl)
    assert windows == {"writer-a": 5, "writer-b": 11}
    assert seeded is False
    payload = _pack_snapshot(9, 0, tbl, {}, seeded=True)
    assert _unpack_snapshot(payload)[4] is True


def test_snapshot_rejects_truncation_everywhere():
    payload = _pack_snapshot(1, 2, _table(), {"w": 1})
    for cut in (0, 10, durable._SNAP_HDR - 1, len(payload) - 1):
        with pytest.raises(wire.WireError):
            _unpack_snapshot(payload[:cut])


def test_snapshot_rejects_bitflip_and_junk():
    payload = bytearray(_pack_snapshot(1, 2, _table(), {"w": 1}))
    flipped = bytearray(payload)
    flipped[durable._SNAP_HDR + 12] ^= 0x40      # body bit flip
    with pytest.raises(wire.WireError):
        _unpack_snapshot(bytes(flipped))
    with pytest.raises(wire.WireError):
        _unpack_snapshot(bytes(payload) + b"junk")   # crc covers length
    bad_magic = struct.pack("<i", 0) + bytes(payload[4:])
    with pytest.raises(wire.WireError):
        _unpack_snapshot(bad_magic)
    bad_version = bytes(payload[:4]) + struct.pack("<i", 99) \
        + bytes(payload[8:])
    with pytest.raises(wire.WireError):
        _unpack_snapshot(bad_version)


def test_delta_roundtrip_and_rejects():
    body, _, _ = _body([1, 2], 1, windows={"w": 7})
    rec = _pack_delta(5, body)
    gen, out, end = _unpack_delta(rec)
    assert (gen, out, end) == (5, body, len(rec))
    # two records back to back parse by offset
    rec2 = rec + _pack_delta(6, body)
    g1, _, off = _unpack_delta(rec2)
    g2, _, end2 = _unpack_delta(rec2, off)
    assert (g1, g2, end2) == (5, 6, len(rec2))
    for cut in (0, 3, durable._DELTA_HDR - 1, len(rec) - 1):
        with pytest.raises(wire.WireError):
            _unpack_delta(rec[:cut])
    flipped = bytearray(rec)
    flipped[durable._DELTA_HDR + 2] ^= 0x01
    with pytest.raises(wire.WireError):
        _unpack_delta(bytes(flipped))
    with pytest.raises(wire.WireError):
        _unpack_delta(struct.pack("<i", 0x7777) + rec[4:])


def test_marker_roundtrip_and_rejects():
    rec = _pack_marker(123)
    assert _unpack_marker(rec) == 123
    for cut in (0, 7, len(rec) - 1):
        with pytest.raises(wire.WireError):
            _unpack_marker(rec[:cut])
    with pytest.raises(wire.WireError):
        _unpack_marker(struct.pack("<i", 1) + rec[4:])
    with pytest.raises(wire.WireError):
        _unpack_marker(rec[:4] + struct.pack("<i", 99) + rec[8:])


# ---------------------------------------------------------------------------
# store cycle: exact ledger, chain discipline, tail_since
# ---------------------------------------------------------------------------

def test_store_cycle_exact_ledger(tmp_path):
    st, _base, expect = _store_with_tail(tmp_path, nsteps=5)
    st.close()
    st2 = CheckpointStore(str(tmp_path))
    point = st2.restore()
    assert point is not None
    assert (point.epoch, point.base_gen, point.gen) == (7, 0, 5)
    assert point.windows == {"w": 3}
    assert len(point.deltas) == 5
    assert np.array_equal(_replay(point), expect)   # bit-exact ledger
    st2.close()


def test_append_requires_chain_and_fresh_base(tmp_path):
    st = CheckpointStore(str(tmp_path))
    body, _, _ = _body([1], 1)
    assert not st.append_delta(1, body)             # no base yet
    st.save_snapshot(0, 0, _table(), {})
    assert not st.append_delta(2, body)             # gap: 0 -> 2
    assert st.append_delta(1, body)
    assert not st.append_delta(3, body)             # gap: 1 -> 3
    assert st.append_delta(2, body)
    st.restore()
    # a recovered tail is never appended to in place
    assert not st.append_delta(3, body)
    st.save_snapshot(0, 2, _table(), {})
    assert st.append_delta(3, body)
    st.close()


def test_tail_since_semantics(tmp_path):
    st, _, _ = _store_with_tail(tmp_path, nsteps=3)
    assert [g for g, _ in st.tail_since(0)] == [1, 2, 3]
    assert [g for g, _ in st.tail_since(2)] == [3]
    assert st.tail_since(3) == []
    assert st.tail_since(-1) is None                # predates the base
    st.close()


def test_counters_advance(tmp_path):
    snaps0 = int(obs.counter("ps_ckpt_snapshots").get_value())
    deltas0 = int(obs.counter("ps_ckpt_deltas").get_value())
    restores0 = int(obs.counter("ps_ckpt_restores").get_value())
    st, _, _ = _store_with_tail(tmp_path, nsteps=4)
    st.restore()
    st.close()
    assert int(obs.counter("ps_ckpt_snapshots").get_value()) == snaps0 + 1
    assert int(obs.counter("ps_ckpt_deltas").get_value()) == deltas0 + 4
    assert int(obs.counter("ps_ckpt_restores").get_value()) == restores0 + 1


def test_compaction_folds_tail_and_retires(tmp_path):
    st, _, expect = _store_with_tail(tmp_path, nsteps=3, keep_bases=1)
    st.save_snapshot(7, 3, expect, {"w": 6})        # compact at gen 3
    names = sorted(os.listdir(tmp_path))
    assert "base-%016d.snap" % 0 not in names       # old base retired
    assert "base-%016d.snap" % 3 in names
    assert "delta-%016d.log" % 0 not in names       # old segment retired
    point = st.restore()
    assert (point.base_gen, point.gen) == (3, 3)
    assert np.array_equal(point.table, expect)
    st.close()


def test_should_compact_threshold(tmp_path):
    st = CheckpointStore(str(tmp_path), compact_bytes=64)
    st.save_snapshot(0, 0, _table(), {})
    assert not st.should_compact()
    body, _, _ = _body(list(range(8)), 1)
    st.append_delta(1, body)
    assert st.should_compact()
    st.save_snapshot(0, 1, _table(), {})
    assert not st.should_compact()                  # tail folded
    st.close()


# ---------------------------------------------------------------------------
# crash-mid-checkpoint: every torn shape restores the last complete record
# ---------------------------------------------------------------------------

def _latest_segment(root):
    segs = sorted(n for n in os.listdir(root)
                  if n.startswith("delta-") and n.endswith(".log"))
    return os.path.join(root, segs[-1])


def test_crash_mid_append_torn_tail(tmp_path):
    st, base, _ = _store_with_tail(tmp_path, nsteps=5)
    st.close()
    seg = _latest_segment(tmp_path)
    with open(seg, "r+b") as f:                     # kill mid-write of rec 5
        f.truncate(os.path.getsize(seg) - 7)
    point = CheckpointStore(str(tmp_path)).restore()
    assert point.gen == 4                           # last COMPLETE record
    expect = base.copy()
    for g in range(1, 5):
        _, ids, grads = _body([g % ROWS, (g + 3) % ROWS], g)
        np.subtract.at(expect, ids, grads)
    assert np.array_equal(_replay(point), expect)


def test_crash_mid_snapshot_falls_back_to_prior_base(tmp_path):
    st, _base, expect = _store_with_tail(tmp_path, nsteps=3)
    st.save_snapshot(7, 3, expect, {"w": 6})        # compaction: base 3
    st.close()
    # the new base is torn mid-write AND a stray .tmp is left behind
    newest = os.path.join(tmp_path, "base-%016d.snap" % 3)
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    with open(newest + ".tmp", "wb") as f:
        f.write(b"\x00" * 10)
    point = CheckpointStore(str(tmp_path)).restore()
    # falls back to base 0 and replays its retained segment chain 1..3
    assert (point.base_gen, point.gen) == (0, 3)
    assert np.array_equal(_replay(point), expect)


def test_crash_mid_compaction_stale_marker_tolerated(tmp_path):
    st, _base, expect = _store_with_tail(tmp_path, nsteps=3)
    st.save_snapshot(7, 3, expect, {"w": 6})
    st.close()
    # crash between writing the base and the marker: marker still names
    # the OLD base — restore trusts the scan, not the marker
    with open(os.path.join(tmp_path, "compact.marker"), "wb") as f:
        f.write(_pack_marker(0))
    point = CheckpointStore(str(tmp_path)).restore()
    assert (point.base_gen, point.gen) == (3, 3)
    assert np.array_equal(point.table, expect)


def test_bitflip_mid_segment_stops_chain_cleanly(tmp_path):
    st, base, _ = _store_with_tail(tmp_path, nsteps=4)
    st.close()
    seg = _latest_segment(tmp_path)
    rec_len = durable._DELTA_HDR + len(_body([0, 1], 1,
                                             windows={"w": 4})[0])
    with open(seg, "r+b") as f:                     # flip a byte in rec 2
        f.seek(rec_len + durable._DELTA_HDR + 5)
        b = f.read(1)
        f.seek(rec_len + durable._DELTA_HDR + 5)
        f.write(bytes([b[0] ^ 0x10]))
    point = CheckpointStore(str(tmp_path)).restore()
    assert point.gen == 1                           # nothing past the flip
    expect = base.copy()
    _, ids, grads = _body([1 % ROWS, 4 % ROWS], 1)
    np.subtract.at(expect, ids, grads)
    assert np.array_equal(_replay(point), expect)


def test_restore_none_without_usable_base(tmp_path):
    st = CheckpointStore(str(tmp_path))
    assert st.restore() is None
    with open(os.path.join(tmp_path, "base-%016d.snap" % 5), "wb") as f:
        f.write(b"garbage")
    with open(os.path.join(tmp_path, "README"), "wb") as f:
        f.write(b"not a checkpoint file")
    assert st.restore() is None
    assert st.load_base() is None
    st.close()


def test_load_base_skips_corrupt_and_lying_files(tmp_path):
    st, base, _ = _store_with_tail(tmp_path, nsteps=1)
    st.close()
    # a newer base whose content says a DIFFERENT gen than its name
    lying = _pack_snapshot(7, 8, _table(1), {})
    with open(os.path.join(tmp_path, "base-%016d.snap" % 9), "wb") as f:
        f.write(lying)
    epoch, gen, tbl, _, _seeded = CheckpointStore(str(tmp_path)).load_base()
    assert (epoch, gen) == (7, 0)
    assert np.array_equal(tbl, base)


# ---------------------------------------------------------------------------
# server integration (native-gated): tee, cold restart, hydration
# ---------------------------------------------------------------------------

VOCAB = 64


def _apply(addr, ids, step, timeout_ms=5000):
    ids = np.asarray(ids, np.int32)
    grads = np.full((ids.size, DIM), 2.0 ** -step, np.float32)
    ch = rpc.Channel(addr, timeout_ms=timeout_ms)
    try:
        ch.call("Ps", "ApplyGrad", bytes(_pack_apply_req(ids, grads)))
    finally:
        ch.close()
    return ids, grads


def _wait(pred, deadline_s=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.mark.needs_native
def test_server_tee_and_cold_restart_exact(tmp_path):
    from brpc_tpu.ps_remote import PsShardServer
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3)
    store = CheckpointStore(str(tmp_path))
    try:
        assert sv.attach_checkpoint(store) is None  # nothing to recover
        for g in range(1, 6):
            _apply(sv.address, [g % VOCAB, (g + 7) % VOCAB], g)
        expect = sv.table.copy()
        gen = sv._install_gen
    finally:
        sv.close()
        store.close()
    # cold restart: fresh process state, same store root
    sv2 = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3)
    store2 = CheckpointStore(str(tmp_path))
    try:
        point = sv2.attach_checkpoint(store2)
        assert point is not None and point.gen == gen
        assert sv2._install_gen == gen
        assert np.array_equal(sv2.table, expect)    # bit-exact ledger
        # the tee re-armed on a fresh base: applies keep checkpointing
        _apply(sv2.address, [1, 2], 9)
        assert store2.last_gen == sv2._install_gen
    finally:
        sv2.close()
        store2.close()


@pytest.mark.needs_native
def test_server_cold_restart_torn_tail_lands_short(tmp_path):
    from brpc_tpu.ps_remote import PsShardServer
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3)
    store = CheckpointStore(str(tmp_path))
    try:
        sv.attach_checkpoint(store)
        for g in range(1, 5):
            _apply(sv.address, [g, g + 1], g)
        before_last = sv.table.copy()               # state at gen 4
        _apply(sv.address, [9, 11], 5)
    finally:
        sv.close()
        store.close()
    seg = _latest_segment(tmp_path)
    with open(seg, "r+b") as f:
        f.truncate(os.path.getsize(seg) - 3)        # tear record 5
    sv2 = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=3)
    store2 = CheckpointStore(str(tmp_path))
    try:
        point = sv2.attach_checkpoint(store2)
        assert point.gen == 4                       # last complete record
        assert np.array_equal(sv2.table, before_last)
    finally:
        sv2.close()
        store2.close()


@pytest.mark.needs_native
def test_hydrate_replica_ships_tail_not_wholesale(tmp_path):
    from brpc_tpu.naming import ReplicaSet
    from brpc_tpu.ps_remote import PsShardServer
    a = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=5)
    b = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=5)
    store = CheckpointStore(str(tmp_path))
    try:
        a.attach_checkpoint(store)
        for g in range(1, 5):
            _apply(a.address, [g, g + 2], g)
        # re-base so the snapshot sits at gen 4 with an empty tail...
        a.attach_checkpoint(store, recover=False)
        for g in range(5, 8):                       # ...then grow gen 5..7
            _apply(a.address, [g, g + 2], g)
        rs = ReplicaSet((a.address, b.address), primary=0)
        b.configure_replication(rs, 1)
        seeded = durable.hydrate_replica(store, b.address)
        assert seeded == 4                          # the base generation
        hyd0 = int(obs.counter("ps_replica_hydrates").get_value())
        syncs0 = int(obs.counter("ps_replica_syncs").get_value())
        a.configure_replication(rs, 0)
        assert _wait(lambda: b._install_gen == a._install_gen)
        a.flush_replication()
        assert np.array_equal(a.table, b.table)
        assert int(obs.counter(
            "ps_replica_hydrates").get_value()) == hyd0 + 1
        # the live primary never shipped a wholesale table image
        assert int(obs.counter(
            "ps_replica_syncs").get_value()) == syncs0
        # writes keep replicating through the hydrated stream
        ids, grads = _apply(a.address, [1, 3], 9)
        a.flush_replication()
        assert np.array_equal(a.table, b.table)
    finally:
        a.close()
        b.close()
        store.close()


@pytest.mark.needs_native
def test_hydrate_destination_split_ships_tail(tmp_path):
    from brpc_tpu.naming import PartitionScheme, ReplicaSet
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding
    from brpc_tpu.reshard import MigrationDriver
    from brpc_tpu import resilience
    src = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0, seed=8, stream=True)
    dst = [PsShardServer(VOCAB, DIM, s, 2, lr=1.0, seed=8, stream=True,
                         importing=True, scheme_version=1)
           for s in range(2)]
    store = CheckpointStore(str(tmp_path))
    sc0 = PartitionScheme(0, (ReplicaSet.of(src.address),))
    sc1 = PartitionScheme(1, tuple(ReplicaSet.of(sv.address)
                                   for sv in dst))
    emb = RemoteEmbedding([sc0], VOCAB, DIM, timeout_ms=10000,
                          retry=resilience.RetryPolicy(
                              max_attempts=4,
                              backoff=resilience.Backoff(base_ms=1,
                                                         max_ms=10),
                              attempt_timeout_ms=500))
    drv = MigrationDriver(sc0, sc1, VOCAB)
    ids = np.arange(VOCAB, dtype=np.int32)
    before = src.table.copy()
    try:
        src.attach_checkpoint(store)
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.5, np.float32))
        src.attach_checkpoint(store, recover=False)   # base at gen 1
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.25,
                                         np.float32))
        half = VOCAB // 2
        for s, sv in enumerate(dst):
            g = durable.hydrate_destination(
                store, sv.address, 1, src.address, 0, s * half, half)
            assert g == 1
        hyd0 = int(obs.counter("ps_migrate_hydrates").get_value())
        syncs0 = int(obs.counter("ps_migrate_syncs_out").get_value())
        drv.start()
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.125,
                                         np.float32))
        drv.wait_caught_up(deadline_s=20)
        drv.cutover()
        emb.set_schemes([sc0.with_(state="draining", weight=0.0), sc1])
        emb.apply_gradients(ids, np.full((VOCAB, DIM), 0.0625,
                                         np.float32))
        expect = before.copy()
        for d in (0.5, 0.25, 0.125, 0.0625):
            expect[ids] -= np.float32(d)
        assert np.array_equal(
            np.concatenate([sv.table for sv in dst]), expect)
        assert int(obs.counter(
            "ps_migrate_hydrates").get_value()) == hyd0 + 2
        # neither destination needed a wholesale range sync
        assert int(obs.counter(
            "ps_migrate_syncs_out").get_value()) == syncs0
    finally:
        drv.close()
        emb.close()
        src.close()
        for sv in dst:
            sv.close()
        store.close()


def test_append_delta_epoch_mismatch_rebases(tmp_path):
    """An epoch bump WITHOUT a wholesale install (a promotion: the
    generation chain continues, only the epoch moves) must re-base —
    restoring the old base would resurrect the stale epoch and
    un-fence retired writers.  ``append_delta(..., epoch=)`` refuses
    the mismatched record; the caller snapshots under the new epoch
    and the chain resumes."""
    st = CheckpointStore(str(tmp_path))
    base = _table(3)
    st.save_snapshot(7, 0, base, {})
    body1, _, _ = _body([1], 1)
    assert st.append_delta(1, body1, epoch=7)
    body2, _, _ = _body([2], 2)
    # promotion bumped the epoch; gen 2 IS the next chain link, yet
    # the record must be refused — the base was written under epoch 7
    assert not st.append_delta(2, body2, epoch=8)
    # the caller's response: fold the current table into a new base
    st.save_snapshot(8, 2, base, {})
    body3, _, _ = _body([3], 3)
    assert st.append_delta(3, body3, epoch=8)
    # epoch-blind callers (legacy) keep appending on the chain
    body4, _, _ = _body([4], 4)
    assert st.append_delta(4, body4)
    point = st.restore()
    assert point is not None
    assert point.epoch == 8 and point.base_gen == 2 and point.gen == 4
    st.close()
