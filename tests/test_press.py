"""The scenario traffic harness (brpc_tpu.press): deterministic
workload generation, zipf skew, burst scheduling, the record/replay
trace format (strict parser), and the live open-loop driver."""

import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from brpc_tpu import press, wire
from brpc_tpu.press import (OP_APPLY, OP_LOOKUP, PressOp, Scenario,
                            build_ops, parse_trace, trace_bytes,
                            zipf_weights)


def _same_ops(a, b) -> bool:
    return len(a) == len(b) and all(
        x.t_us == y.t_us and x.op == y.op and np.array_equal(x.ids,
                                                             y.ids)
        for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# generation
# ---------------------------------------------------------------------------

def test_build_ops_deterministic_and_sorted_arrivals():
    sc = Scenario(duration_s=0.5, qps=400, batch=8, seed=3)
    a, b = build_ops(sc, 256), build_ops(sc, 256)
    assert _same_ops(a, b) and len(a) > 100
    ts = [op.t_us for op in a]
    assert ts == sorted(ts)
    assert all(0 <= t < 500_000 for t in ts)
    assert all(op.ids.size == 8 and op.ids.dtype == np.int32
               for op in a)


def test_read_write_mix_follows_fraction():
    sc = Scenario(duration_s=1.0, qps=500, read_fraction=0.7, seed=1)
    ops = build_ops(sc, 128)
    writes = sum(1 for op in ops if op.op == OP_APPLY)
    frac = writes / len(ops)
    assert 0.2 < frac < 0.4                    # ~0.3 expected


def test_zipf_skew_concentrates_on_hot_ranks():
    w = zipf_weights(1000, 1.2)
    assert abs(w.sum() - 1.0) < 1e-9
    assert w[0] > 50 * w[999]
    sc = Scenario(duration_s=1.0, qps=400, batch=16, zipf_s=1.2,
                  seed=2)
    ops = build_ops(sc, 1000)
    counts = np.bincount(
        np.concatenate([op.ids for op in ops]), minlength=1000)
    # the hottest decile draws a large multiple of the coldest
    assert counts[:100].sum() > 5 * counts[900:].sum()


def test_burst_windows_arrive_denser_than_steady():
    sc = Scenario(duration_s=2.0, qps=100, burst_qps=1000,
                  burst_every_s=1.0, burst_len_s=0.25, seed=4)
    ops = build_ops(sc, 64)
    in_burst = sum(1 for op in ops
                   if (op.t_us / 1e6) % 1.0 < 0.25)
    out_burst = len(ops) - in_burst
    # 0.5s of burst at 10x the rate vs 1.5s steady
    assert in_burst > 2 * out_burst


# ---------------------------------------------------------------------------
# trace record/replay
# ---------------------------------------------------------------------------

def test_trace_roundtrip_exact(tmp_path):
    sc = Scenario(duration_s=0.3, qps=300, batch=5,
                  read_fraction=0.8, seed=9)
    ops = build_ops(sc, 512)
    path = os.path.join(tmp_path, "t.trace")
    press.save_trace(path, ops, seed=9, vocab=512, dim=16)
    meta, back = press.load_trace(path)
    assert meta == {"seed": 9, "vocab": 512, "dim": 16}
    assert _same_ops(ops, back)


def test_trace_rejects_corruption():
    ops = [PressOp(10, OP_LOOKUP, np.arange(3, dtype=np.int32))]
    blob = trace_bytes(ops, seed=1, vocab=64, dim=4)
    with pytest.raises(wire.WireError):
        parse_trace(blob[:-1])                 # truncated record
    with pytest.raises(wire.WireError):
        parse_trace(blob + b"x")               # trailing junk
    bad_magic = b"\x00" + blob[1:]
    with pytest.raises(wire.WireError):
        parse_trace(bad_magic)
    # a count lying past the bytes present
    lied = bytearray(blob)
    struct.pack_into("<i", lied, 28, 99)       # header count field
    with pytest.raises(wire.WireError):
        parse_trace(bytes(lied))
    # a negative id count inside a record
    neg = bytearray(blob)
    struct.pack_into("<i", neg, 32 + 12, -1)   # record nids
    with pytest.raises(wire.WireError):
        parse_trace(bytes(neg))
    # an unknown op kind
    kind = bytearray(blob)
    struct.pack_into("<i", kind, 32 + 8, 9)    # record op field
    with pytest.raises(wire.WireError):
        parse_trace(bytes(kind))


def test_trace_schema_parity_with_hand_rolled_packers():
    """The hand-rolled press packers are byte-identical to the
    declared schemas (the wire-contract parity discipline)."""
    hdr = wire.REGISTRY["press_header"]
    assert press._pack_press_header(seed=5, vocab=100, dim=8,
                                    count=2) == hdr.pack({
        "magic": wire.PRESS_MAGIC, "version": press.PRESS_VERSION,
        "seed": 5, "vocab": 100, "dim": 8, "count": 2})
    rec = wire.REGISTRY["press_record"]
    op = PressOp(77, OP_APPLY, np.array([1, 5, 9], np.int32))
    assert press._pack_press_record(op) == rec.pack({
        "t_us": 77, "op": OP_APPLY, "nids": 3, "ids": op.ids})


# ---------------------------------------------------------------------------
# the live driver (native)
# ---------------------------------------------------------------------------

@pytest.mark.needs_native
def test_run_press_steady_under_capacity():
    from brpc_tpu.ps_remote import PsShardServer
    srv = PsShardServer(256, 8, 0, 1)
    try:
        sc = Scenario(duration_s=0.6, qps=200, batch=8,
                      read_fraction=0.8, seed=5)
        ops = build_ops(sc, 256)
        rep = press.run_press(srv.address, ops, 8, deadline_ms=200,
                              stamp_deadline=True)
        assert rep["n"] == len(ops)
        assert rep["availability"] == 1.0
        assert rep["goodput_qps"] > 0
        assert rep["p99_ms"] <= 200
        assert rep["stamped"] is True
        # the writes actually landed: the table moved
        assert srv._install_gen > 0
    finally:
        srv.close()


@pytest.mark.needs_native
def test_run_press_retry_on_limit_absorbs_admission_spikes():
    """A 1-slot gate under a concurrency-2 schedule: bare runs shed,
    the ELIMIT-retry client policy absorbs them."""
    from brpc_tpu.ps_remote import PsShardServer
    srv = PsShardServer(256, 8, 0, 1, limiter="constant:1")
    try:
        # all ops due at ~t=0: guaranteed admission collisions
        ops = [PressOp(i * 100, OP_LOOKUP,
                       np.arange(4, dtype=np.int32))
               for i in range(40)]
        bare = press.run_press(srv.address, ops, 8, deadline_ms=500)
        retried = press.run_press(srv.address, ops, 8,
                                  deadline_ms=500, retry_on_limit=3,
                                  limit_backoff_ms=2.0)
        assert retried["availability"] >= bare["availability"]
        assert retried["availability"] >= 0.97
    finally:
        srv.close()


def test_cli_record_then_replay_file(tmp_path):
    path = os.path.join(tmp_path, "cli.trace")
    rc = press.main(["--record", path, "--qps", "300", "--duration",
                     "0.2", "--vocab", "128", "--seed", "6"])
    assert rc == 0
    meta, ops = press.load_trace(path)
    assert meta["vocab"] == 128 and len(ops) > 20
    # the same seed regenerates the identical stream
    again = build_ops(Scenario(duration_s=0.2, qps=300, seed=6), 128)
    assert _same_ops(ops, again)


def test_cli_is_runnable_as_module():
    out = subprocess.run(
        [sys.executable, "-m", "brpc_tpu.press", "--help"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "rpc_press" in out.stdout


@pytest.mark.needs_native
def test_run_press_multi_channel_pacer():
    """channels=N paces over N native connections round-robin (the
    multi-core client-ceiling satellite): same determinism and SLO
    surface, per-channel retry legs, and a report that names the
    fan-out.  Relative-budget stamping (v2) rides the same path."""
    from brpc_tpu.ps_remote import PsShardServer
    srv = PsShardServer(256, 8, 0, 1)
    try:
        sc = Scenario(duration_s=0.5, qps=240, batch=8,
                      read_fraction=0.7, seed=9)
        ops = build_ops(sc, 256)
        rep = press.run_press(srv.address, ops, 8, deadline_ms=300,
                              stamp_deadline=True,
                              stamp_mode="relative", channels=3)
        assert rep["channels"] == 3
        assert rep["stamp_mode"] == "relative"
        assert rep["n"] == len(ops)
        assert rep["availability"] == 1.0
        assert srv._install_gen > 0     # v2-stamped writes landed
        # single-channel equivalence: the op stream is identical, so
        # the table advanced the same number of write batches
        gen_multi = srv._install_gen
        rep1 = press.run_press(srv.address, ops, 8, deadline_ms=300,
                               channels=1)
        assert rep1["channels"] == 1
        assert rep1["availability"] == 1.0
        assert srv._install_gen == 2 * gen_multi
    finally:
        srv.close()
