"""End-to-end tpu_ps: native sharded PS servers + JAX gradients (the
BASELINE #5 workload on loopback — SURVEY §4 multi-node-in-one-process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

VOCAB, DIM, SHARDS = 64, 16, 4


@pytest.fixture(scope="module")
def cluster():
    servers = [PsShardServer(VOCAB, DIM, i, SHARDS, lr=0.5)
               for i in range(SHARDS)]
    emb = RemoteEmbedding([s.address for s in servers], VOCAB, DIM)
    yield servers, emb
    emb.close()
    for s in servers:
        s.close()


def test_lookup_matches_shards(cluster):
    servers, emb = cluster
    ids = np.array([0, 15, 16, 63, 17], np.int32)
    rows = emb.lookup(ids)
    rows_per = VOCAB // SHARDS
    for i, rid in enumerate(ids):
        shard = servers[rid // rows_per]
        np.testing.assert_array_equal(rows[i],
                                      shard.table[rid % rows_per])


def test_remote_training_converges(cluster):
    servers, emb = cluster
    rng = np.random.default_rng(0)
    # distinct ids: each row has ONE consistent target, so the loss can
    # actually reach ~0 (duplicates with conflicting targets cannot)
    ids = rng.permutation(VOCAB)[:32].astype(np.int32).reshape(8, 4)
    targets = rng.standard_normal((8, 4, DIM)).astype(np.float32) * 0.1

    @jax.jit
    def loss_and_grad(rows, tgt):
        loss = jnp.mean((rows - tgt) ** 2)
        # sum-loss gradient: per-row step size independent of batch size
        return loss, jax.grad(
            lambda r: 0.5 * jnp.sum((r - tgt) ** 2))(rows)

    losses = []
    for _ in range(25):
        rows = jnp.asarray(emb.lookup(ids))
        loss, grads = loss_and_grad(rows, jnp.asarray(targets))
        losses.append(float(loss))
        emb.apply_gradients(ids, np.asarray(grads))
    assert losses[-1] < losses[0] * 0.5


def test_duplicate_ids_accumulate(cluster):
    servers, emb = cluster
    rid = 5
    before = servers[0].table[rid].copy()
    ids = np.array([rid, rid], np.int32)
    grads = np.ones((2, DIM), np.float32)
    emb.apply_gradients(ids, grads)
    after = servers[0].table[rid]
    # both contributions land (scatter-add, not last-write-wins)
    np.testing.assert_allclose(after, before - 0.5 * 2.0, rtol=1e-5)
