"""Pallas kernel tests (interpret mode on CPU; the same kernel compiles for
TPU — guide /opt/skills/guides/pallas_guide.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.models import llama
from brpc_tpu.ops import flash_attention


def _inputs(key, b=2, t=128, hq=4, hkv=2, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), dtype)
    k = jax.random.normal(kk, (b, t, hkv, d), dtype)
    v = jax.random.normal(kv, (b, t, hkv, d), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _inputs(jax.random.PRNGKey(0))
    want = llama.attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_uneven_blocks():
    q, k, v = _inputs(jax.random.PRNGKey(1), t=64)
    want = llama.attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, block_q=16, block_k=64, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16():
    q, k, v = _inputs(jax.random.PRNGKey(2), t=64, dtype=jnp.bfloat16)
    want = llama.attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=5e-2, atol=5e-2)
