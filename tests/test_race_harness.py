"""Tests for the dynamic lock-order race detector
(brpc_tpu.analysis.race): inversion cycles with both stacks, the
blocking-native-call warning, and the zero-overhead-off contract."""

import threading

import pytest

from brpc_tpu.analysis import race


@pytest.fixture(autouse=True)
def _isolated_race_state():
    race.clear()
    yield
    race.set_enabled(None)
    race.set_sample(None)
    race.clear()


# ---- off-mode contract ----

def test_plain_lock_when_env_unset(monkeypatch):
    monkeypatch.delenv("BRPC_TPU_RACECHECK", raising=False)
    race.set_enabled(None)
    lock = race.checked_lock("steady.state")
    assert type(lock) is type(threading.Lock())
    assert not isinstance(lock, race.CheckedLock)


def test_env_var_turns_on_checked_locks(monkeypatch):
    monkeypatch.setenv("BRPC_TPU_RACECHECK", "1")
    race.set_enabled(None)
    lock = race.checked_lock("checked.state")
    assert isinstance(lock, race.CheckedLock)


def test_env_var_off_values(monkeypatch):
    for off in ("0", "false", "off", ""):
        monkeypatch.setenv("BRPC_TPU_RACECHECK", off)
        race.set_enabled(None)
        assert not isinstance(race.checked_lock("x"), race.CheckedLock)


def test_fabric_locks_are_plain_by_default():
    """The obs tier built its locks at import time with RACECHECK unset
    (the pytest environment) — steady state must carry plain locks."""
    from brpc_tpu import obs
    a = obs.Adder()
    assert not isinstance(a._mu, race.CheckedLock)


# ---- CheckedLock behaves like threading.Lock ----

def test_checked_lock_api():
    race.set_enabled(True)
    lock = race.checked_lock("api.lock")
    assert not lock.locked()
    assert lock.acquire()
    assert lock.locked()
    lock.release()
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()
    assert lock.acquire(blocking=False)
    assert not lock.acquire(blocking=False)  # non-reentrant, like Lock
    lock.release()


# ---- lock-order inversion ----

def test_inversion_cycle_reported_with_both_stacks():
    race.set_enabled(True)
    lock_a = race.checked_lock("inv.A")
    lock_b = race.checked_lock("inv.B")

    def order_ab():
        with lock_a:
            with lock_b:
                pass

    def order_ba():
        with lock_b:
            with lock_a:
                pass

    t1 = threading.Thread(target=order_ab)
    t1.start()
    t1.join()
    assert race.findings() == []  # one consistent order: no cycle yet

    t2 = threading.Thread(target=order_ba)
    t2.start()
    t2.join()
    inversions = [f for f in race.findings() if f.kind == "lock-inversion"]
    assert len(inversions) == 1
    f = inversions[0]
    assert {"inv.A", "inv.B"} <= set(f.locks)
    assert "potential" in f.message and "deadlock" in f.message
    report = f.format()
    # both acquisition stacks present: the A->B order and the B->A order
    assert "order_ab" in report
    assert "order_ba" in report


def test_consistent_order_stays_clean():
    race.set_enabled(True)
    lock_a = race.checked_lock("ok.A")
    lock_b = race.checked_lock("ok.B")
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert race.findings() == []


def test_transitive_cycle_detected():
    race.set_enabled(True)
    la = race.checked_lock("tr.A")
    lb = race.checked_lock("tr.B")
    lc = race.checked_lock("tr.C")
    with la:
        with lb:
            pass
    with lb:
        with lc:
            pass
    assert race.findings() == []
    with lc:
        with la:  # closes A -> B -> C -> A
            pass
    inversions = [f for f in race.findings() if f.kind == "lock-inversion"]
    assert len(inversions) == 1
    assert {"tr.A", "tr.B", "tr.C"} <= set(inversions[0].locks)


def test_same_name_sibling_instances_not_an_edge():
    """Two reducers' '_mu' locks share a name; nesting them is not an
    ordering violation (there are thousands of same-name instances)."""
    race.set_enabled(True)
    m1 = race.checked_lock("sib.mu")
    m2 = race.checked_lock("sib.mu")
    with m1:
        with m2:
            pass
    with m2:
        with m1:
            pass
    assert race.findings() == []


# ---- sampling mode ----

def test_sample_every_env_and_override(monkeypatch):
    monkeypatch.setenv("BRPC_TPU_RACECHECK_SAMPLE", "8")
    race.set_sample(None)
    assert race.sample_every() == 8
    race.set_sample(3)
    assert race.sample_every() == 3
    monkeypatch.setenv("BRPC_TPU_RACECHECK_SAMPLE", "not-a-number")
    race.set_sample(None)
    assert race.sample_every() == 1  # bad values degrade to full capture
    monkeypatch.setenv("BRPC_TPU_RACECHECK_SAMPLE", "0")
    assert race.sample_every() == 1  # clamped


def test_sampled_inversion_still_detected_with_real_edge_stacks():
    """Edge and cycle detection are exact under sampling: a NEW ordering
    edge captures its acquiring stack lazily even when the acquisition
    was sampled out."""
    race.set_enabled(True)
    race.set_sample(1_000_000)  # only each lock's FIRST acquire is eager
    lock_a = race.checked_lock("smp.A")
    lock_b = race.checked_lock("smp.B")
    # burn the first (eagerly captured) acquisitions outside any nesting
    for lock in (lock_a, lock_b):
        for _ in range(3):
            with lock:
                pass
    assert race.findings() == []

    def order_ab():
        with lock_a:
            with lock_b:
                pass

    def order_ba():
        with lock_b:
            with lock_a:
                pass

    order_ab()
    order_ba()
    inversions = [f for f in race.findings() if f.kind == "lock-inversion"]
    assert len(inversions) == 1
    report = inversions[0].format()
    # both edge-acquisition stacks were captured lazily at first
    # observation despite sampling
    assert "order_ab" in report
    assert "order_ba" in report


def test_sampled_out_held_stack_uses_placeholder():
    race.set_enabled(True)
    race.set_sample(1_000_000)
    lock = race.checked_lock("smp.held")
    with lock:
        pass  # first acquire: captured eagerly
    with lock:  # second acquire: sampled out, no edge to rescue it
        race.note_blocking("brt_channel_call")
    (f,) = [x for x in race.findings() if x.kind == "blocking-call"]
    assert any(race.SAMPLED_OUT.strip() in s for s in f.stacks.values())


def test_full_capture_unaffected_by_default_sample():
    race.set_enabled(True)
    # tier-1's conftest exports a sampled default for the HANDLE ledger;
    # pin full capture explicitly — the property under test is that
    # sample_every()==1 never yields a placeholder stack.
    race.set_sample(1)
    assert race.sample_every() == 1
    lock = race.checked_lock("smp.full")
    with lock:
        race.note_blocking("brt_device_fetch")
    (f,) = [x for x in race.findings() if x.kind == "blocking-call"]
    assert not any(race.SAMPLED_OUT.strip() in s
                   for s in f.stacks.values())


# ---- blocking native calls under a lock ----

def test_blocking_call_under_lock_flagged():
    race.set_enabled(True)
    lock = race.checked_lock("blk.L")
    with lock:
        race.note_blocking("brt_channel_call")
    flagged = [f for f in race.findings() if f.kind == "blocking-call"]
    assert len(flagged) == 1
    f = flagged[0]
    assert f.locks == ["blk.L"]
    assert "brt_channel_call" in f.message
    assert "serializes fiber workers" in f.message
    # repeat of the same shape dedups
    with lock:
        race.note_blocking("brt_channel_call")
    assert len([f for f in race.findings()
                if f.kind == "blocking-call"]) == 1


def test_blocking_call_without_lock_clean():
    race.set_enabled(True)
    race.note_blocking("brt_channel_call")
    assert race.findings() == []


@pytest.mark.needs_native
def test_blocking_rpc_call_detected_end_to_end():
    """Holding a checked lock across a real Channel.call gets flagged
    through the rpc.py hook (native-gated)."""
    from brpc_tpu import rpc

    race.set_enabled(True)
    srv = rpc.Server()
    srv.add_service("Echo", lambda method, req: req)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}")
    lock = race.checked_lock("e2e.held")
    try:
        with lock:
            assert ch.call("Echo", "Echo", b"x") == b"x"
    finally:
        ch.close()
        srv.close()
    flagged = [f for f in race.findings() if f.kind == "blocking-call"]
    assert any("brt_channel_call" in f.message and "e2e.held" in f.locks
               for f in flagged)


# ---- readers/writer lock (checked_rwlock) ----

def test_rwlock_plain_when_checking_off(monkeypatch):
    monkeypatch.delenv("BRPC_TPU_RACECHECK", raising=False)
    race.set_enabled(None)
    rw = race.checked_rwlock("rw.off")
    assert isinstance(rw, race.RWLock)
    assert not isinstance(rw, race.CheckedRWLock)
    race.set_enabled(True)
    assert isinstance(race.checked_rwlock("rw.on"), race.CheckedRWLock)


@pytest.mark.parametrize("factory", ["plain", "checked"])
def test_rwlock_readers_share_writers_exclude(factory):
    """Two readers hold the lock at the same instant; a writer waits for
    both, then holds alone.  Same contract for the plain and the checked
    variant."""
    import time

    if factory == "checked":
        race.set_enabled(True)
    rw = (race.CheckedRWLock("rw.sem") if factory == "checked"
          else race.RWLock())
    both_in = threading.Barrier(3, timeout=5)
    release = threading.Event()
    state = {"write_entered_at": None, "readers_out_at": None}

    def reader():
        with rw.read():
            both_in.wait()       # proves BOTH readers are inside at once
            release.wait(5)
        # last reader out stamps the time

    def writer():
        with rw.write():
            state["write_entered_at"] = time.monotonic()

    r1 = threading.Thread(target=reader)
    r2 = threading.Thread(target=reader)
    r1.start()
    r2.start()
    both_in.wait()               # readers are concurrent — no deadlock
    w = threading.Thread(target=writer)
    w.start()
    time.sleep(0.05)             # writer is parked behind the readers
    assert state["write_entered_at"] is None
    state["readers_out_at"] = time.monotonic()
    release.set()
    for t in (r1, r2, w):
        t.join(5)
    assert state["write_entered_at"] >= state["readers_out_at"]


def test_rwlock_write_preference_blocks_new_readers():
    """A pending writer gates NEW readers (write-preferring, like the
    native FiberRWLock) — a read stream cannot starve the writer."""
    import time

    rw = race.RWLock()
    in_read = threading.Event()
    release_first = threading.Event()
    order = []

    def first_reader():
        with rw.read():
            in_read.set()
            release_first.wait(5)

    def writer():
        with rw.write():
            order.append("w")

    def late_reader():
        with rw.read():
            order.append("r")

    t1 = threading.Thread(target=first_reader)
    t1.start()
    in_read.wait(5)
    tw = threading.Thread(target=writer)
    tw.start()
    time.sleep(0.05)             # writer is now a registered waiter
    tr = threading.Thread(target=late_reader)
    tr.start()
    time.sleep(0.05)
    release_first.set()
    for t in (t1, tw, tr):
        t.join(5)
    assert order[0] == "w"       # the pending writer beat the late reader


def test_checked_rwlock_inversion_with_plain_lock():
    """Read and write sides feed the order graph under the rwlock's one
    name, so a read-vs-write inversion against another lock closes a
    cycle exactly like two plain locks."""
    race.set_enabled(True)
    rw = race.checked_rwlock("rwinv.A")
    mu = race.checked_lock("rwinv.B")
    with rw.read():
        with mu:
            pass
    assert race.findings() == []
    with mu:
        with rw.write():
            pass
    inversions = [f for f in race.findings() if f.kind == "lock-inversion"]
    assert len(inversions) == 1
    assert {"rwinv.A", "rwinv.B"} <= set(inversions[0].locks)


def test_checked_rwlock_read_held_across_blocking_call_flagged():
    race.set_enabled(True)
    rw = race.checked_rwlock("rwblk.L")
    with rw.read():
        race.note_blocking("brt_device_execute")
    flagged = [f for f in race.findings() if f.kind == "blocking-call"]
    assert len(flagged) == 1
    assert flagged[0].locks == ["rwblk.L"]


def test_checked_rwlock_same_name_read_then_write_not_an_edge():
    """Sibling same-name holds stay exempt for rwlocks too (the per-name
    edge keying, not a reentrancy endorsement)."""
    race.set_enabled(True)
    a = race.checked_rwlock("rwsib.mu")
    b = race.checked_rwlock("rwsib.mu")
    with a.read():
        with b.write():
            pass
    assert race.findings() == []


def test_report_text():
    race.set_enabled(True)
    assert "no findings" in race.report()
    lock = race.checked_lock("rep.L")
    with lock:
        race.note_blocking("brt_device_fetch")
    assert "blocking-call" in race.report()
