import jax
import jax.numpy as jnp
import numpy as np
import optax

from brpc_tpu.models import llama
from brpc_tpu.parallel import make_mesh, shard_batch, shard_params


def test_forward_shapes_and_finite():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = jax.jit(lambda p, t: llama.forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
    t2 = t1.at[0, -1].set(9)
    l1 = llama.forward(params, t1, cfg)
    l2 = llama.forward(params, t2, cfg)
    np.testing.assert_allclose(l1[:, :-1], l2[:, :-1], rtol=1e-5, atol=1e-5)


def test_train_step_reduces_loss():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)
    step = jax.jit(llama.make_train_step(cfg, opt))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
    _, _, loss0 = step(params, opt_state, tokens)
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, tokens)
    assert float(loss) < float(loss0)


def test_sharded_train_step_matches_single_device():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    opt = optax.sgd(1e-2)
    tokens = np.asarray(
        jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, cfg.vocab_size)
    )
    step = jax.jit(llama.make_train_step(cfg, opt))

    # single device
    p1, _, loss1 = step(params, opt.init(params), jnp.asarray(tokens))

    # dp=4 × tp=2 mesh
    mesh = make_mesh({"tp": 2})
    sp = shard_params(params, llama.param_specs(cfg), mesh)
    st = shard_batch(tokens, llama.batch_specs(), mesh)
    p2, _, loss2 = step(sp, opt.init(sp), st)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-4)
    flat1 = jax.tree_util.tree_leaves(p1)
    flat2 = jax.tree_util.tree_leaves(p2)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=2e-2, atol=2e-2
        )


def test_dryrun_multichip():
    # jax 0.4.x's GSPMD partitioner returns a wrong PRIMAL loss for this
    # exact composition (3-axis dp*tp*sp mesh + value_and_grad; the plain
    # forward agrees with the single-device reference, the value_and_grad
    # one is off by ~2.7) — reproduced with dense attention and no
    # shard_map anywhere, so it's the partitioner, not this repo's code.
    # Fixed upstream by the jax 0.5+ partitioner rewrite.
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        import pytest

        pytest.skip("jax<0.5 GSPMD miscompiles value_and_grad primal on "
                    "3-axis meshes (verified against plain forward)")
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
