"""Replicated PS shards + the redirecting breaker: fail over, don't
fail fast.

Covers the tentpole end to end, everything driven by deterministic
:class:`brpc_tpu.fault.FaultPlan` rules (``fault.kill_rules`` is the
kill-primary / kill-replica lever):

- replica read parity — after the sync-ack apply barrier, ANY replica
  answers a Lookup byte-identical to the primary (the propagated
  batches replay the primary's exact float ops);
- primary kill → client-driven fenced promotion → ZERO failed lookups
  under sustained load (reads redirect to the surviving replica while
  the breaker isolates the corpse; writes fail over to the promoted
  backup);
- fenced stale-primary rejection — a demoted-but-unaware primary's
  propagation is refused with EFENCED and it demotes itself, so a
  write accepted by a stale primary is never ACKED;
- redirect-vs-reject breaker behavior — the same open breaker re-routes
  in redirect mode and raises ``EBREAKEROPEN`` in legacy mode;
- idempotent framed push replay — the per-writer seq window makes a
  reconnect's replayed frame a no-op instead of a double apply;
- prober revival returns a demoted replica to the read set.
"""

import struct
import time

import numpy as np
import pytest

from brpc_tpu import fault, obs, resilience, rpc
from brpc_tpu.naming import ReplicaSet, parse_shard_tag, shard_tag
from brpc_tpu.ps_remote import (PsShardServer, RemoteEmbedding,
                                _pack_apply_req, _pack_lookup_req,
                                _pack_stream_frame)

pytestmark = pytest.mark.needs_native

VOCAB, DIM = 256, 8


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)
    fault.clear()


def _cluster(nshards=2, nrep=2, **kw):
    """nshards x nrep replicated cluster, replication configured with
    replica 0 as boot primary.  Returns (servers[s][r], replica_sets)."""
    servers = [[PsShardServer(VOCAB, DIM, s, nshards, **kw)
                for _ in range(nrep)] for s in range(nshards)]
    sets = []
    for s in range(nshards):
        rs = ReplicaSet(tuple(sv.address for sv in servers[s]), primary=0)
        sets.append(rs)
        for r, sv in enumerate(servers[s]):
            sv.configure_replication(rs, r)
    return servers, sets


def _close_all(servers):
    for row in servers:
        for sv in row:
            sv.close()


def _retry_policy(attempts=3, attempt_ms=300):
    return resilience.RetryPolicy(
        max_attempts=attempts,
        backoff=resilience.Backoff(base_ms=1, max_ms=10),
        attempt_timeout_ms=attempt_ms)


# ---------------------------------------------------------------------------
# naming: replica tags
# ---------------------------------------------------------------------------

def test_shard_tag_roundtrip():
    assert shard_tag(1, 4) == "1/4"                    # legacy form
    assert shard_tag(1, 4, 2) == "1/4/2"
    assert parse_shard_tag("1/4") == (1, 4, 0)
    assert parse_shard_tag("1/4/2") == (1, 4, 2)
    assert parse_shard_tag("not-a-tag") is None
    assert parse_shard_tag("1/4/x") is None


def test_replica_set_validation():
    with pytest.raises(ValueError):
        ReplicaSet(())
    with pytest.raises(ValueError):
        ReplicaSet(("a",), primary=1)
    rs = ReplicaSet.of("127.0.0.1:1")
    assert rs.addresses == ("127.0.0.1:1",) and rs.primary == 0
    assert ReplicaSet.of(rs) is rs
    assert ReplicaSet.of(["a", "b"]).addresses == ("a", "b")


# ---------------------------------------------------------------------------
# read parity + propagation
# ---------------------------------------------------------------------------

def test_replica_read_parity_after_apply_barrier():
    servers, sets = _cluster(nshards=2, nrep=2)
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000)
    try:
        ids = np.arange(64, dtype=np.int32) * 4
        # First write: the backups' delta streams establish (full Sync)
        # — propagation is EVENTUAL until then, so poll for parity.
        emb.apply_gradients(ids, np.ones((64, DIM), np.float32))
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
                np.array_equal(servers[s][0].table, servers[s][1].table)
                for s in range(2)):
            time.sleep(0.01)
        # Steady state: the unary apply IS the barrier (sync
        # replication over the established streams) — every replica
        # answers byte-identical rows the moment the apply returns.
        emb.apply_gradients(ids, np.full((64, DIM), 2.0, np.float32))
        for s in range(2):
            owned = np.arange(s * 128, s * 128 + 128, dtype=np.int32)
            req = bytes(_pack_lookup_req(owned))
            answers = []
            for sv in servers[s]:
                ch = rpc.Channel(sv.address, timeout_ms=5000)
                try:
                    answers.append(ch.call("Ps", "Lookup", req))
                finally:
                    ch.close()
            assert answers[0] == answers[1]
            assert np.array_equal(servers[s][0].table,
                                  servers[s][1].table)
    finally:
        emb.close()
        _close_all(servers)


def test_streamed_push_propagates_and_stays_byte_identical():
    servers, sets = _cluster(nshards=2, nrep=2, stream=True)
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    try:
        ids = np.arange(VOCAB, dtype=np.int32)
        for k in range(4):
            emb.push_gradients(ids, np.full((VOCAB, DIM), float(k + 1),
                                            np.float32))
        emb.flush_gradients()   # applied everywhere; first sync may lag
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not all(
                np.array_equal(servers[s][0].table, servers[s][1].table)
                for s in range(2)):
            time.sleep(0.01)
        for s in range(2):
            assert np.array_equal(servers[s][0].table,
                                  servers[s][1].table)
        assert servers[0][0]._install_gen > 0
        assert servers[0][0]._install_gen == servers[0][1]._install_gen
    finally:
        emb.close()
        _close_all(servers)


def test_backup_rejects_direct_write():
    servers, sets = _cluster(nshards=1, nrep=2)
    try:
        backup = servers[0][1]
        ch = rpc.Channel(backup.address, timeout_ms=5000)
        try:
            with pytest.raises(rpc.RpcError) as ei:
                ch.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                    np.arange(4, dtype=np.int32),
                    np.ones((4, DIM), np.float32))))
            assert ei.value.code == resilience.ENOTPRIMARY
        finally:
            ch.close()
    finally:
        _close_all(servers)


# ---------------------------------------------------------------------------
# kill-primary: promotion under sustained load
# ---------------------------------------------------------------------------

def test_primary_kill_promotion_zero_failed_lookups():
    servers, sets = _cluster(nshards=2, nrep=2)
    emb = RemoteEmbedding(
        sets, VOCAB, DIM, timeout_ms=10000, retry=_retry_policy(),
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=4, min_samples=2,
                                      min_isolation_ms=50),
            redirect=True),
        health_check=True, health_interval_ms=20)
    ids = np.arange(128, dtype=np.int32) * 2
    grads = np.ones((128, DIM), np.float32)
    try:
        emb.apply_gradients(ids, grads)      # warm: streams + replicas
        prim = servers[0][0].address
        fault.install(fault.FaultPlan(fault.kill_rules(prim), seed=3))
        # sustained load with the primary dead: every batch must
        # succeed — redirect + failover, never an exception
        t_end = time.monotonic() + 1.0
        reads = writes = 0
        while time.monotonic() < t_end:
            emb.lookup(ids)
            reads += 1
            emb.apply_gradients(ids, grads)
            writes += 1
        assert reads > 10 and writes > 10
        # the backup was promoted with a fencing epoch...
        assert servers[0][1].is_primary
        assert servers[0][1].epoch >= 1
        assert int(obs.counter("ps_client_failovers").get_value()) >= 1
        # ...and reads were REDIRECTED around the corpse, not failed
        assert int(obs.counter("rpc_breaker_redirects").get_value()) > 0
        fault.clear()
        # the prober revives the killed replica back into the read set
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and emb._isolated(prim):
            time.sleep(0.02)
        assert not emb._isolated(prim)
        # the revived replica is fenced into the backup role by the new
        # primary's propagation; writes keep landing everywhere
        emb.apply_gradients(ids, grads)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and servers[0][0].is_primary:
            time.sleep(0.02)
        assert not servers[0][0].is_primary
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


def test_promotion_preserves_acked_updates_exactly():
    """Zero lost updates: everything the client was ACKED before,
    during, and after a failover is present in the final tables —
    exact-arithmetic sums make a single lost delta detectable."""
    servers, sets = _cluster(nshards=1, nrep=2, lr=1.0)
    emb = RemoteEmbedding(
        sets, VOCAB, DIM, timeout_ms=10000, retry=_retry_policy(),
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=4, min_samples=2,
                                      min_isolation_ms=50),
            redirect=True))
    ids = np.arange(VOCAB, dtype=np.int32)
    delta = np.full((VOCAB, DIM), 0.5, np.float32)  # exactly representable
    try:
        before = servers[0][0].table.copy()
        acked = 0
        emb.apply_gradients(ids, delta)
        acked += 1
        # let the backup's first full Sync land (propagation is eventual
        # until the delta stream is established) before the kill
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not np.array_equal(
                servers[0][0].table, servers[0][1].table):
            time.sleep(0.01)
        prim = servers[0][0].address
        fault.install(fault.FaultPlan(fault.kill_rules(prim), seed=5))
        for _ in range(3):
            emb.apply_gradients(ids, delta)   # fails over, then lands
            acked += 1
        fault.clear()
        for _ in range(2):
            emb.apply_gradients(ids, delta)
            acked += 1
        # flush barrier on the CURRENT primary, then exact parity
        cur = sets[0].addresses[emb._primary_idx[0]]
        ch = rpc.Channel(cur, timeout_ms=5000)
        try:
            ch.call("Ps", "Flush", b"")
        finally:
            ch.close()
        # replicate the server's per-apply float32 op exactly: each
        # acked batch was ONE in-place subtract of 0.5 (lr=1.0)
        expect = before.copy()
        for _ in range(acked):
            expect[ids] -= np.float32(0.5)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and not np.array_equal(
                servers[0][0].table, servers[0][1].table):
            time.sleep(0.02)
        assert np.array_equal(servers[0][1].table, expect)
        assert np.array_equal(servers[0][0].table, servers[0][1].table)
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# fencing
# ---------------------------------------------------------------------------

def test_fenced_stale_primary_rejected_and_demoted():
    servers, sets = _cluster(nshards=1, nrep=2)
    old, new = servers[0][0], servers[0][1]
    try:
        # wait for the (eagerly connected) delta stream: the fence
        # notification rides its reply half
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not any(
                p.stream is not None and not p.need_sync
                for p in old._replicator._peers):
            time.sleep(0.01)
        # Partition the old primary's replication CONTROL plane so the
        # new primary cannot inform it (otherwise the eager propagation
        # demotes it instantly) — the old data stream stays up.
        fault.install(fault.FaultPlan([
            fault.FaultRule(action="error", side="server", service="Ps",
                            method="Sync", endpoint=old.address,
                            error_code=1009),
            fault.FaultRule(action="error", side="server", service="Ps",
                            method="ReplicaApply", endpoint=old.address,
                            error_code=1009)], seed=1))
        # Out-of-band promotion (epoch 1): the old primary doesn't know.
        ch_new = rpc.Channel(new.address, timeout_ms=5000)
        try:
            ch_new.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch_new.close()
        assert new.is_primary and new.epoch == 1
        assert old.is_primary            # stale, unaware
        # A write to the stale primary must NOT be acked: its
        # propagation is fenced (EFENCED) and it demotes itself.
        ch_old = rpc.Channel(old.address, timeout_ms=5000)
        try:
            with pytest.raises(rpc.RpcError) as ei:
                ch_old.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                    np.arange(4, dtype=np.int32),
                    np.ones((4, DIM), np.float32))))
            assert ei.value.code == resilience.EFENCED
            # demoted: the next write is refused outright
            with pytest.raises(rpc.RpcError) as ei2:
                ch_old.call("Ps", "ApplyGrad", bytes(_pack_apply_req(
                    np.arange(4, dtype=np.int32),
                    np.ones((4, DIM), np.float32))))
            assert ei2.value.code == resilience.ENOTPRIMARY
        finally:
            ch_old.close()
        # demoted by the fence; it adopts the new EPOCH later, from the
        # new primary's first Sync (nothing has shipped yet)
        assert not old.is_primary
        assert int(obs.counter("ps_replica_fenced").get_value()) >= 1
    finally:
        _close_all(servers)


def test_stale_promote_epoch_rejected():
    servers, _ = _cluster(nshards=1, nrep=2)
    try:
        ch = rpc.Channel(servers[0][1].address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 2))
            with pytest.raises(rpc.RpcError) as ei:
                ch.call("Ps", "Promote", struct.pack("<q", 2))
            assert ei.value.code == resilience.EFENCED
        finally:
            ch.close()
    finally:
        _close_all(servers)


# ---------------------------------------------------------------------------
# redirect vs reject
# ---------------------------------------------------------------------------

def test_redirect_vs_reject_breaker_behavior():
    servers, sets = _cluster(nshards=1, nrep=2)
    ids = np.arange(16, dtype=np.int32)
    prim = servers[0][0].address
    try:
        # REDIRECT mode: an open breaker on the primary re-routes reads
        # to the live sibling instead of raising.
        reg = resilience.BreakerRegistry(min_working=1, redirect=True)
        emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=5000,
                              breakers=reg)
        try:
            reg.breaker_for(prim).isolate()
            before = int(
                obs.counter("rpc_breaker_redirects").get_value())
            out = emb.lookup(ids)
            assert out.shape == (16, DIM)
            assert int(obs.counter("rpc_breaker_redirects").get_value()
                       ) > before
        finally:
            emb.close()
        # REJECT mode (redirect=False): same topology, same open
        # breaker — the legacy fail-fast contract.
        reg2 = resilience.BreakerRegistry(min_working=1, redirect=False)
        emb2 = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=5000,
                               breakers=reg2)
        try:
            reg2.breaker_for(prim).isolate()
            with pytest.raises(rpc.RpcError) as ei:
                emb2.lookup(ids)
            assert ei.value.code == resilience.EBREAKEROPEN
        finally:
            emb2.close()
        # every replica isolated: redirect has nowhere to go and rejects
        reg3 = resilience.BreakerRegistry(min_working=0, redirect=True)
        emb3 = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=5000,
                               breakers=reg3)
        try:
            for a in sets[0].addresses:
                reg3.breaker_for(a).isolate()
            with pytest.raises(rpc.RpcError) as ei:
                emb3.lookup(ids)
            assert ei.value.code == resilience.EBREAKEROPEN
        finally:
            emb3.close()
    finally:
        _close_all(servers)


def test_reads_route_by_score_across_replicas():
    """The locality-aware LB half: with a slow primary, the scorer
    shifts read traffic to the fast replica (no breaker involved)."""
    servers, sets = _cluster(nshards=1, nrep=2)
    prim = servers[0][0].address
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000)
    ids = np.arange(32, dtype=np.int32)
    try:
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="delay", side="server", service="Ps",
            method="Lookup", endpoint=prim, delay_ms=25)], seed=11))
        for _ in range(12):
            emb.lookup(ids)
        snap = emb.scorer.snapshot()
        backup = servers[0][1].address
        assert snap[backup]["ewma_ms"] < snap[prim]["ewma_ms"]
        # the slow replica's share collapses but it still gets probed
        assert emb.scorer.pick(list(sets[0].addresses)) == backup
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# idempotent framed push (satellite: at-least-once -> exactly-once)
# ---------------------------------------------------------------------------

def test_framed_push_replay_is_idempotent():
    servers, sets = _cluster(nshards=1, nrep=1, stream=True, lr=1.0)
    sv = servers[0][0]
    before = sv.table.copy()
    ids = np.arange(8, dtype=np.int32)
    body = bytes(_pack_apply_req(ids, np.full((8, DIM), 0.5,
                                              np.float32)))
    ch = rpc.Channel(sv.address, timeout_ms=5000)
    try:
        st = ch.stream("Ps", "StreamApply", b"writer-1")
        (high,) = struct.unpack("<q", st.response)
        assert high == 0
        st.write(_pack_stream_frame(1, 0, 0, body))
        st.close()
        assert st.join(timeout_s=5)
        # reconnect: the server answers the seq high-water mark...
        st2 = ch.stream("Ps", "StreamApply", b"writer-1")
        (high2,) = struct.unpack("<q", st2.response)
        assert high2 == 1
        # ...and a replayed frame 1 is DROPPED, not double-applied
        drops0 = int(obs.counter("ps_stream_dedup_drops").get_value())
        st2.write(_pack_stream_frame(1, 0, 0, body))
        st2.write(_pack_stream_frame(2, 0, 0, body))
        st2.close()
        assert st2.join(timeout_s=5)
        assert int(obs.counter("ps_stream_dedup_drops").get_value()) \
            == drops0 + 1
        # exactly two applies of -0.5 (lr=1.0): exact arithmetic,
        # replayed per-apply (two in-place subtracts, like the server)
        expect = before.copy()
        expect[ids] -= np.float32(0.5)
        expect[ids] -= np.float32(0.5)
        assert np.array_equal(sv.table, expect)
    finally:
        ch.close()
        _close_all(servers)


def test_push_gradients_dedups_across_reconnect():
    """The client replays the in-doubt frame after a dropped-setup
    reconnect; the per-writer window means the table ends EXACTLY one
    apply per push, never two, whichever side the break fell on."""
    servers, sets = _cluster(nshards=1, nrep=1, stream=True, lr=1.0)
    sv = servers[0][0]
    before = sv.table.copy()
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy(attempts=4))
    ids = np.arange(16, dtype=np.int32)
    delta = np.full((16, DIM), 0.25, np.float32)
    try:
        emb.push_gradients(ids, delta)     # opens the stream
        emb.flush_gradients()
        # kill the NEXT setup once: the push after flush must reconnect
        fault.install(fault.FaultPlan([fault.FaultRule(
            action="error", side="client", service="Ps",
            method="StreamApply", error_code=1009, max_hits=1)],
            seed=2))
        pushes = 4
        for _ in range(pushes):
            emb.push_gradients(ids, delta)
        emb.flush_gradients()
        expect = before.copy()
        for _ in range(pushes + 1):
            expect[ids] -= np.float32(0.25)
        assert np.array_equal(sv.table, expect)
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# zombie fencing + window inheritance + lossy-promotion refusal
# ---------------------------------------------------------------------------

def test_zombie_primary_push_stream_fenced_no_lost_acks():
    """A primary demoted WHILE carrying a push stream must not keep
    applying frames into a table the new primary's Sync will erase: the
    per-frame fence drops them, the flush barrier detects the applied-
    window shortfall on the live primary, replays the unacked tail onto
    it, and only then acks — exact arithmetic proves every pushed delta
    landed exactly once."""
    servers, sets = _cluster(nshards=1, nrep=2, stream=True, lr=1.0)
    old, new = servers[0][0], servers[0][1]
    before = old.table.copy()
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy(attempts=4))
    ids = np.arange(VOCAB, dtype=np.int32)
    delta = np.full((VOCAB, DIM), 0.5, np.float32)
    try:
        emb.push_gradients(ids, delta)
        emb.flush_gradients()            # frame 1 acked everywhere
        # Out-of-band promotion: the old primary still holds the
        # client's push stream and may not know it is a zombie yet.
        ch = rpc.Channel(new.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch.close()
        emb.push_gradients(ids, delta)   # frames 2..3 race the fence
        emb.push_gradients(ids, delta)
        emb.flush_gradients()            # must fail over + replay
        expect = before.copy()
        for _ in range(3):
            expect[ids] -= np.float32(0.5)
        assert np.array_equal(new.table, expect)
        assert emb._primary_idx[0] == 1
    finally:
        emb.close()
        _close_all(servers)


def test_seq_window_survives_failover_no_double_apply():
    """The per-writer dedup window is replicated WITH the batches it
    covers: after an out-of-band promotion the backup's inherited
    window already spans both unflushed frames, so the client's flush
    barrier confirms without resending — no double apply, no replay."""
    servers, sets = _cluster(nshards=1, nrep=2, stream=True, lr=1.0)
    prim, backup = servers[0][0], servers[0][1]
    before = prim.table.copy()
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy())
    ids = np.arange(16, dtype=np.int32)
    delta = np.full((16, DIM), 0.25, np.float32)
    try:
        emb.push_gradients(ids, delta)
        emb.push_gradients(ids, delta)
        # the wire writer key is scheme- and shard-qualified (seq
        # spaces must not collide inside migrated dedup windows)
        wkey = emb._stream_writer_key(emb._wv, 0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                backup._writer_applied.get(wkey, 0) < 2:
            time.sleep(0.01)
        assert backup._writer_applied.get(wkey, 0) == 2
        assert backup._writer_seqs.get(wkey, 0) == 2
        ch = rpc.Channel(backup.address, timeout_ms=5000)
        try:
            ch.call("Ps", "Promote", struct.pack("<q", 1))
        finally:
            ch.close()
        replays0 = int(obs.counter("ps_push_replays").get_value())
        emb.flush_gradients()
        assert int(obs.counter("ps_push_replays").get_value()) \
            == replays0
        expect = before.copy()
        expect[ids] -= np.float32(0.25)
        expect[ids] -= np.float32(0.25)
        assert np.array_equal(backup.table, expect)
    finally:
        emb.close()
        _close_all(servers)


def test_failover_refuses_gen_behind_promotion():
    """Single-fault loss window closed client-side: writes acked by the
    primary alone (backup partitioned from replication) raise the
    client's acked-gen floor; when the primary then dies, promoting the
    gen-behind backup would lose those acks — the failover REFUSES
    loudly instead of promoting silently."""
    servers = [[PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
                for _ in range(2)]]
    prim, backup = servers[0][0], servers[0][1]
    rs = ReplicaSet((prim.address, backup.address), primary=0)
    # Partition the backup's replication plane BEFORE the replica set
    # is configured, so the primary acks every write alone.
    fault.install(fault.FaultPlan([
        fault.FaultRule(action="error", side="server", service="Ps",
                        method="Sync", endpoint=backup.address,
                        error_code=1009),
        fault.FaultRule(action="error", side="server", service="Ps",
                        method="ReplicaApply", endpoint=backup.address,
                        error_code=1009)], seed=7))
    prim.configure_replication(rs, 0)
    backup.configure_replication(rs, 1)
    emb = RemoteEmbedding(
        [rs], VOCAB, DIM, timeout_ms=2000, retry=_retry_policy(),
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=4, min_samples=2,
                                      min_isolation_ms=50),
            redirect=True))
    ids = np.arange(8, dtype=np.int32)
    grads = np.ones((8, DIM), np.float32)
    try:
        for _ in range(3):
            emb.apply_gradients(ids, grads)
        assert emb._gen_seen[0] >= 1
        assert backup._install_gen == 0
        # primary dies with the backup still partitioned: the only
        # candidate is gen-behind
        fault.install(fault.FaultPlan(
            list(fault.kill_rules(prim.address)) + [
                fault.FaultRule(action="error", side="server",
                                service="Ps", method="Sync",
                                endpoint=backup.address,
                                error_code=1009),
                fault.FaultRule(action="error", side="server",
                                service="Ps", method="ReplicaApply",
                                endpoint=backup.address,
                                error_code=1009)], seed=7))
        refusal = None
        for _ in range(40):
            try:
                emb.apply_gradients(ids, grads)
            except rpc.RpcError as e:
                if e.code == resilience.EBREAKEROPEN and \
                        "refusing" in str(e):
                    refusal = e
                    break
        assert refusal is not None
        assert backup._install_gen == 0      # never lossily promoted
        assert not backup.is_primary
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# concurrent retry re-fan (satellite: max(shard), not sum)
# ---------------------------------------------------------------------------

def test_failed_shards_refan_concurrently():
    nshards = 4
    servers = [PsShardServer(VOCAB, DIM, s, nshards)
               for s in range(nshards)]
    addrs = [sv.address for sv in servers]
    emb = RemoteEmbedding(addrs, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy(attempts=3))
    ids = np.arange(128, dtype=np.int32) * 2   # touches all shards
    try:
        # shards 1 and 2: first attempt errors instantly, the RETRY
        # (the first call that reaches the server) is slow — if retries
        # ran sequentially the batch would pay 2 x delay.
        delay_ms = 120
        rules = []
        for a in (addrs[1], addrs[2]):
            rules.append(fault.FaultRule(
                action="error", side="client", endpoint=a,
                error_code=1009, max_hits=1))
            rules.append(fault.FaultRule(
                action="delay", side="server", service="Ps",
                method="Lookup", endpoint=a, delay_ms=delay_ms))
        fault.install(fault.FaultPlan(rules, seed=9))
        retries0 = int(obs.counter("rpc_retries").get_value())
        t0 = time.perf_counter()
        out = emb.lookup(ids)
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        assert out.shape == (128, DIM)
        assert int(obs.counter("rpc_retries").get_value()) \
            == retries0 + 2
        # concurrent: ~1x delay + overhead; sequential would be >= 2x
        assert elapsed_ms < 2 * delay_ms - 20, elapsed_ms
    finally:
        fault.clear()
        emb.close()
        for sv in servers:
            sv.close()


# ---------------------------------------------------------------------------
# registry-driven replica discovery
# ---------------------------------------------------------------------------

def test_from_registry_builds_replica_sets():
    from brpc_tpu.naming import NamingClient

    servers, sets = _cluster(nshards=2, nrep=2)
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    port = reg_server.start("127.0.0.1:0")
    try:
        nc = NamingClient(f"127.0.0.1:{port}")
        for s in range(2):
            for r in range(2):
                nc.register("ps", servers[s][r].address,
                            tag=shard_tag(s, 2, r), heartbeat=False)
        emb = RemoteEmbedding.from_registry(
            f"127.0.0.1:{port}", "ps", VOCAB, DIM, timeout_ms=5000)
        try:
            assert emb.n == 2
            for s in range(2):
                assert emb.replica_sets[s].addresses == \
                    sets[s].addresses
                assert emb.replica_sets[s].primary == 0
            assert emb.replicated
            out = emb.lookup(np.arange(32, dtype=np.int32))
            assert out.shape == (32, DIM)
        finally:
            emb.close()
        nc.close()
    finally:
        reg_server.close()
        _close_all(servers)


# ---------------------------------------------------------------------------
# quorum replication (ISSUE 13): majority-ack writes + majority promotion
# ---------------------------------------------------------------------------

def test_quorum_auto_resolution():
    """configure_replication(quorum="auto") resolves to the majority
    for >=3-replica groups and to the legacy connected-only barrier
    for pairs; explicit forms pass through / validate."""
    servers, _ = _cluster(nshards=1, nrep=3, lr=1.0)
    try:
        assert all(sv._quorum == 2 for sv in servers[0])
    finally:
        _close_all(servers)
    servers, _ = _cluster(nshards=1, nrep=2, lr=1.0)
    try:
        assert all(sv._quorum is None for sv in servers[0])
    finally:
        _close_all(servers)
    sv = PsShardServer(VOCAB, DIM, 0, 1, lr=1.0)
    try:
        rs = ReplicaSet((sv.address, "127.0.0.1:9", "127.0.0.1:10"))
        with pytest.raises(ValueError):
            sv.configure_replication(rs, 0, quorum=7)
        sv.configure_replication(rs, 0, quorum="majority")
        assert sv._quorum == 2
    finally:
        sv.close()


def test_quorum_bootstrap_kill_loses_nothing():
    """THE bootstrap loss window: with 3 replicas and a majority
    quorum, the very first acked write already sits on >=2 replicas —
    killing the primary right after it can no longer lose it (the
    legacy connected-only barrier acked on the primary alone until the
    backups' first Sync landed)."""
    servers, sets = _cluster(nshards=1, nrep=3, lr=1.0)
    flat = [sv for row in servers for sv in row]
    prim = servers[0][0]
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                          retry=_retry_policy(attempts=4))
    ids = np.arange(16, dtype=np.int32)
    before = prim.table.copy()
    try:
        # the FIRST write: the quorum barrier blocks until a backup
        # really holds it (its connect Sync covers the gen)
        emb.apply_gradients(ids, np.full((16, DIM), 0.5, np.float32))
        fault.install(fault.FaultPlan(
            fault.kill_rules(prim.address), seed=13))
        # failover must find the acked write on a surviving replica
        emb.apply_gradients(ids, np.full((16, DIM), 0.25, np.float32))
        expect = before.copy()
        for d in (0.5, 0.25):
            expect[ids] -= np.float32(d)
        new_prim = next(sv for sv in flat
                        if sv is not prim and sv.is_primary)
        assert np.array_equal(new_prim.table, expect)
        assert np.array_equal(emb.lookup(ids), expect[ids])
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


def test_quorum_unreachable_fails_loudly_never_acks():
    """With every backup black-holed a quorum write must FAIL (loud
    unavailability) — and the failed write must not have mutated the
    acked state observable after the backups return."""
    servers, sets = _cluster(nshards=1, nrep=3, lr=1.0)
    prim = servers[0][0]
    prim.repl_ack_timeout_s = 0.4
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=3000,
                          retry=_retry_policy(attempts=2,
                                              attempt_ms=1500))
    ids = np.arange(8, dtype=np.int32)
    try:
        emb.apply_gradients(ids, np.full((8, DIM), 0.5, np.float32))
        fault.install(fault.FaultPlan(
            fault.kill_rules(servers[0][1].address)
            + fault.kill_rules(servers[0][2].address), seed=17))
        # sever the ESTABLISHED propagation streams too (fault rules
        # only gate call paths): acks stop flowing and reconnects die
        rpc.debug_fail_connections(servers[0][1].address)
        rpc.debug_fail_connections(servers[0][2].address)
        with pytest.raises(rpc.RpcError):
            emb.apply_gradients(ids, np.full((8, DIM), 0.25,
                                             np.float32))
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


def test_promotion_requires_majority_sweep():
    """For a 3-replica group, losing TWO replicas leaves a minority —
    promotion must refuse loudly (a sub-majority sweep cannot prove it
    intersects the write quorum); with exactly a majority reachable it
    proceeds."""
    servers, sets = _cluster(nshards=1, nrep=3, lr=1.0)
    emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=3000,
                          retry=_retry_policy(attempts=2,
                                              attempt_ms=400))
    ids = np.arange(8, dtype=np.int32)
    try:
        emb.apply_gradients(ids, np.full((8, DIM), 0.5, np.float32))
        # kill primary AND one backup: 1 of 3 reachable < majority 2
        fault.install(fault.FaultPlan(
            fault.kill_rules(servers[0][0].address)
            + fault.kill_rules(servers[0][1].address), seed=19))
        with pytest.raises(rpc.RpcError):
            emb.apply_gradients(ids, np.full((8, DIM), 0.25,
                                             np.float32))
        # the surviving minority was not promoted behind our back
        assert not servers[0][2].is_primary
        # majority restored (primary still dead): promotion proceeds
        fault.install(fault.FaultPlan(
            fault.kill_rules(servers[0][0].address), seed=19))
        emb.apply_gradients(ids, np.full((8, DIM), 0.25, np.float32))
        assert servers[0][1].is_primary or servers[0][2].is_primary
    finally:
        fault.clear()
        emb.close()
        _close_all(servers)


def test_staggered_bringup_no_self_demotion():
    """THE bring-up race the churn bench found: with real delays
    between the replicas' configure_replication calls, the primary's
    eager connect used to hit a NOT-YET-CONFIGURED backup, read its
    default primary flag as a stale-primary EFENCED, demote itself,
    and stop(join=False) closed its channel set under a sibling
    worker's in-flight Sync — a native use-after-free.  Now an
    unconfigured backup rejects retriably, the primary stays primary,
    and teardown always joins workers before closing channels."""
    for _ in range(3):   # the race was timing-dependent: iterate
        servers = [[PsShardServer(VOCAB, DIM, s, 2, lr=1.0)
                    for _ in range(3)] for s in range(2)]
        try:
            sets = []
            for s in range(2):
                rs = ReplicaSet(tuple(sv.address for sv in servers[s]),
                                primary=0)
                sets.append(rs)
                for r, sv in enumerate(servers[s]):
                    sv.configure_replication(rs, r)
                    time.sleep(0.003)   # the staggered bring-up
            time.sleep(0.3)             # eager connects settle
            assert all(servers[s][0].is_primary for s in range(2))
            assert not any(sv.is_primary
                           for s in range(2) for sv in servers[s][1:])
            emb = RemoteEmbedding(sets, VOCAB, DIM, timeout_ms=10000,
                                  retry=_retry_policy(attempts=4))
            try:
                ids = np.arange(8, dtype=np.int32)
                before = servers[0][0].table.copy()
                emb.apply_gradients(ids, np.full((8, DIM), 0.5,
                                                 np.float32))
                expect = before.copy()
                expect[ids] -= np.float32(0.5)
                assert np.array_equal(servers[0][0].table, expect)
            finally:
                emb.close()
        finally:
            _close_all(servers)
