"""Collective/parallelism tests on the 8-device CPU mesh (SURVEY §4: the
loopback-multi-node pattern — virtual devices stand in for chips; the
driver separately dry-runs the real multi-chip path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from brpc_tpu.models import llama
from brpc_tpu.parallel import (
    CollectiveChannel,
    make_mesh,
    pipeline_apply,
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh({"dp": 8})


def test_all_reduce(mesh8):
    chan = CollectiveChannel(mesh8, "dp")
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    out = jax.jit(chan.all_reduce)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0))


def test_all_gather_identity(mesh8):
    chan = CollectiveChannel(mesh8, "dp")
    x = jnp.arange(32, dtype=jnp.float32).reshape(16, 2)
    out = jax.jit(chan.all_gather)(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter_then_gather(mesh8):
    chan = CollectiveChannel(mesh8, "dp")
    x = jnp.arange(64, dtype=jnp.float32).reshape(16, 4)
    rs = jax.jit(chan.reduce_scatter)(x)
    # replicated input summed 8x, scattered: gathering returns 8*x
    back = jax.jit(chan.all_gather)(rs)
    np.testing.assert_allclose(np.asarray(back), 8 * np.asarray(x))


def test_shift_ring(mesh8):
    chan = CollectiveChannel(mesh8, "dp")
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = jax.jit(lambda a: chan.shift(a, 1))(x)
    # device i's value moves to device i+1 (ring)
    np.testing.assert_allclose(
        np.asarray(out).ravel(), np.roll(np.arange(8), 1)
    )


def test_map_reduce(mesh8):
    chan = CollectiveChannel(mesh8, "dp")
    x = jnp.ones((8, 4), jnp.float32)
    out = jax.jit(
        lambda a: chan.map_reduce(lambda s: jnp.sum(s * 2), a)
    )(x)
    assert float(out) == 64.0


def _attn_inputs(key, b=2, t=64, hq=4, hkv=2, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, hkv, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh({"sp": 8})
    q, k, v = _attn_inputs(jax.random.PRNGKey(0))
    want = llama.attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis="sp", causal=causal
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(causal):
    mesh = make_mesh({"sp": 2})
    q, k, v = _attn_inputs(jax.random.PRNGKey(1))
    want = llama.attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ulysses_attention(
            q, k, v, mesh=mesh, axis="sp", causal=causal
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_grads():
    mesh = make_mesh({"sp": 4})
    q, k, v = _attn_inputs(jax.random.PRNGKey(2), t=32)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, mesh=mesh, axis="sp") ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(llama.attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=1e-3, atol=1e-3)


def test_pipeline_matches_sequential():
    mesh = make_mesh({"pp": 4})
    n_stages, width = 4, 16
    key = jax.random.PRNGKey(3)
    w = jax.random.normal(key, (n_stages, width, width), jnp.float32) * 0.3

    def stage_fn(params, x):
        return jnp.tanh(x @ params)

    x = jax.random.normal(jax.random.PRNGKey(4), (16, width), jnp.float32)
    want = x
    for s in range(n_stages):
        want = stage_fn(w[s], want)
    got = jax.jit(
        lambda w, x: pipeline_apply(
            stage_fn, w, x, mesh=mesh, axis="pp", microbatches=8
        )
    )(w, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
