"""Instrumentation-cost micro-benchmark: what does a metric write cost?

The obs hooks sit on every RPC/PS hot path, so their per-op overhead IS a
perf number for this repo — this starts the BENCH trajectory with the
observer's own cost. Emits BENCH_obs.json next to the BENCH_r*.json
series.

Run: JAX_PLATFORMS=cpu python bench_obs.py
"""

from __future__ import annotations

import json
import os
import time

from brpc_tpu import obs
from brpc_tpu.obs import rpcz


def _per_op_ns(fn, n: int, *, repeats: int = 5) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        fn(n)
        best = min(best, (time.perf_counter_ns() - t0) / n)
    return best


def main() -> dict:
    adder = obs.Adder()
    maxer = obs.Maxer()
    rec = obs.LatencyRecorder()
    ring = rpcz.SpanRing(capacity=1024)

    def bench_adder(n):
        add = adder.add
        for _ in range(n):
            add(1)

    def bench_maxer(n):
        up = maxer.update
        for i in range(n):
            up(i & 1023)

    def bench_record(n):
        r = rec.record
        for _ in range(n):
            r(0.000123)

    def bench_span(n):
        for _ in range(n):
            with rpcz.span("Bench", "op", ring=ring):
                pass

    def bench_disabled_gate(n):
        enabled = obs.enabled
        for _ in range(n):
            if enabled():
                pass

    n = 200_000
    result = {
        "metric": "obs_overhead",
        "unit": "ns/op",
        "adder_add_ns": round(_per_op_ns(bench_adder, n), 1),
        "maxer_update_ns": round(_per_op_ns(bench_maxer, n), 1),
        "latency_record_ns": round(_per_op_ns(bench_record, n), 1),
        "span_ns": round(_per_op_ns(bench_span, n // 10), 1),
        "enabled_gate_ns": round(_per_op_ns(bench_disabled_gate, n), 1),
        "ops_per_measurement": n,
    }

    # dump cost at a realistic registry size (dashboards scrape this)
    reg = obs.Registry()
    for i in range(200):
        a = obs.Adder()
        a.add(i)
        reg.expose(f"bench_var_{i}", a)
    t0 = time.perf_counter_ns()
    for _ in range(100):
        reg.dump_exposed()
    result["dump_exposed_200vars_us"] = round(
        (time.perf_counter_ns() - t0) / 100 / 1e3, 1)

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_obs.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
