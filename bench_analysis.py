"""checked_lock overhead micro-benchmark: the race harness must be free
when it is off.

`checked_lock()` with BRPC_TPU_RACECHECK unset returns a plain
``threading.Lock`` — per-op cost must be indistinguishable from
constructing the lock directly (it IS the same object type).  The
checked (RACECHECK=1) cost is reported alongside for scale, in both
full-capture mode (every acquisition captures its stack, ~26µs) and
sampled mode (``BRPC_TPU_RACECHECK_SAMPLE=N``: every Nth stack, first
observation of an edge always captured) — sampling must land at ≤ 1/5
of the full-capture cost to be usable under production-shaped load.
Emits BENCH_analysis.json next to the BENCH_obs.json series.

Run: JAX_PLATFORMS=cpu python bench_analysis.py
"""

from __future__ import annotations

import json
import os
import threading
import time

from brpc_tpu.analysis import race


def _per_op_ns(fn, n: int, *, repeats: int = 5) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        fn(n)
        best = min(best, (time.perf_counter_ns() - t0) / n)
    return best


def _acquire_release_loop(lock):
    def run(n):
        acquire = lock.acquire
        release = lock.release
        for _ in range(n):
            acquire()
            release()
    return run


def _with_loop(lock):
    def run(n):
        for _ in range(n):
            with lock:
                pass
    return run


def main() -> dict:
    race.set_enabled(None)
    os.environ.pop("BRPC_TPU_RACECHECK", None)

    plain = threading.Lock()
    off = race.checked_lock("bench.off")
    race.set_enabled(True)
    on = race.checked_lock("bench.on")
    sampled = race.checked_lock("bench.sampled")
    race.set_enabled(None)

    n = 200_000
    sample_n = 64
    plain_ns = _per_op_ns(_acquire_release_loop(plain), n)
    off_ns = _per_op_ns(_acquire_release_loop(off), n)
    on_ns = _per_op_ns(_acquire_release_loop(on), n // 10)
    race.set_sample(sample_n)
    try:
        sampled_ns = _per_op_ns(_acquire_release_loop(sampled), n // 10)
    finally:
        race.set_sample(None)

    result = {
        "metric": "checked_lock_overhead",
        "unit": "ns/op (acquire+release)",
        "threading_lock_ns": round(plain_ns, 1),
        "checked_lock_off_ns": round(off_ns, 1),
        "checked_lock_on_ns": round(on_ns, 1),
        "checked_lock_sampled_ns": round(sampled_ns, 1),
        "racecheck_sample_every": sample_n,
        "sampled_over_full_ratio": round(sampled_ns / on_ns, 4),
        "sampled_within_one_fifth_of_full": sampled_ns <= on_ns / 5,
        "off_is_plain_lock_type": type(off) is type(plain),
        "off_over_plain_ratio": round(off_ns / plain_ns, 3),
        "with_stmt_plain_ns": round(_per_op_ns(_with_loop(plain), n), 1),
        "with_stmt_off_ns": round(_per_op_ns(_with_loop(off), n), 1),
        "ops_per_measurement": n,
    }

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_analysis.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
