"""Analysis-tier overhead micro-benchmarks: the race harness AND the
handle ledger must be free when they are off.

`checked_lock()` with BRPC_TPU_RACECHECK unset returns a plain
``threading.Lock`` — per-op cost must be indistinguishable from
constructing the lock directly (it IS the same object type).  The
checked (RACECHECK=1) cost is reported alongside for scale, in both
full-capture mode (every acquisition captures its stack, ~26µs) and
sampled mode (``BRPC_TPU_RACECHECK_SAMPLE=N``: every Nth stack, first
observation of an edge always captured) — sampling must land at ≤ 1/5
of the full-capture cost to be usable under production-shaped load.

The handle ledger (BRPC_TPU_HANDLECHECK) follows the same contract:
with the env unset, ``rpc._load()`` wraps NOTHING — the native ABI is
the raw CFuncPtr, ~1.0x by construction (measured against a wrapped-
but-disabled proxy as the worst case); enabled, the per-handle cost is
stack capture, and sampling (the same RACECHECK knob) bounds it exactly
like the lock harness.  Emits BENCH_analysis.json next to the
BENCH_obs.json series.

Run: JAX_PLATFORMS=cpu python bench_analysis.py
"""

from __future__ import annotations

import json
import os
import threading
import time

from brpc_tpu.analysis import fuzz as wire_fuzz
from brpc_tpu.analysis import handles, race


def _per_op_ns(fn, n: int, *, repeats: int = 5) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter_ns()
        fn(n)
        best = min(best, (time.perf_counter_ns() - t0) / n)
    return best


def _acquire_release_loop(lock):
    def run(n):
        acquire = lock.acquire
        release = lock.release
        for _ in range(n):
            acquire()
            release()
    return run


def _with_loop(lock):
    def run(n):
        for _ in range(n):
            with lock:
                pass
    return run


def _note_pair_loop():
    def run(n):
        create = handles.note_create
        destroy = handles.note_destroy
        for i in range(n):
            create("bench", 0x10000 + (i & 1023))
            destroy("bench", 0x10000 + (i & 1023))
    return run


def _bench_handles() -> dict:
    """Per-handle ledger cost: disabled (the off-mode early return —
    the worst case of a wrapped-but-disabled ABI; true off-mode installs
    no wrapper at all), full capture, and sampled capture."""
    handles.clear()
    handles.set_enabled(False)
    race.set_sample(None)
    n = 100_000
    off_ns = _per_op_ns(_note_pair_loop(), n)
    handles.set_enabled(True)
    full_ns = _per_op_ns(_note_pair_loop(), n // 20)
    race.set_sample(64)
    try:
        sampled_ns = _per_op_ns(_note_pair_loop(), n // 4)
    finally:
        race.set_sample(None)
        handles.set_enabled(None)
        handles.clear()
    out = {
        "unit": "ns per create+destroy pair",
        "ledger_disabled_ns": round(off_ns, 1),
        "ledger_full_ns": round(full_ns, 1),
        "ledger_sampled_ns": round(sampled_ns, 1),
        "handlecheck_sample_every": 64,
        "sampled_over_full_ratio": round(sampled_ns / full_ns, 4),
        "sampled_within_one_fifth_of_full": sampled_ns <= full_ns / 5,
    }
    # the real off-mode claim: with HANDLECHECK unset nothing is wrapped
    # — measure the raw native pair vs the same pair behind a DISABLED
    # wrapper (the upper bound of what off-mode could ever cost)
    try:
        from brpc_tpu import rpc
        lib = rpc._load()
        new = lib.brt_event_new
        destroy = lib.brt_event_destroy
        if isinstance(new, rpc._LedgerFn):  # env had HANDLECHECK on
            new, destroy = new._fn, destroy._fn
        wrapped_new = rpc._LedgerFn(new, "event", True)
        wrapped_destroy = rpc._LedgerFn(destroy, "event", False)

        def raw(n):
            for _ in range(n):
                destroy(new())

        handles.set_enabled(False)

        def wrapped(n):
            for _ in range(n):
                wrapped_destroy(wrapped_new())

        raw_ns = _per_op_ns(raw, 20_000)
        wrapped_off_ns = _per_op_ns(wrapped, 20_000)
        handles.set_enabled(None)
        out["native_event_pair_raw_ns"] = round(raw_ns, 1)
        out["native_event_pair_wrapped_off_ns"] = round(wrapped_off_ns, 1)
        out["wrapped_off_over_raw_ratio"] = round(wrapped_off_ns / raw_ns,
                                                  3)
        # with HANDLECHECK unset _load() installs NO wrapper: the ABI is
        # the raw CFuncPtr itself — off-mode is 1.0x by construction,
        # and the wrapped_off ratio above is the bound it never pays
        out["off_mode_installs_no_wrapper"] = not isinstance(
            rpc._load().brt_event_new, rpc._LedgerFn) or \
            handles.enabled()
    except Exception as e:  # noqa: BLE001 — no native core: skip
        out["native_event_pair"] = f"skipped: {e}"
    return out


def _bench_native_extract() -> dict:
    """Cross-language tier throughput: how fast the clang-free C++
    extractor + the three native checks sweep the whole ``cpp/capi``
    surface (files/sec over full check runs), and the current in-tree
    findings count (0 = the ABI contract holds)."""
    from brpc_tpu.analysis import native

    root = os.path.dirname(os.path.abspath(__file__))
    files = native.default_cpp_files(root)
    if not files:
        return {"skipped": "no cpp/capi tree next to this script"}
    repeats, best = 5, float("inf")
    findings = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        findings = native.run_native_checks(files, root)
        best = min(best, time.perf_counter() - t0)
    total_lines = 0
    for p in files:
        with open(p, "r", encoding="utf-8") as f:
            total_lines += sum(1 for _ in f)
    return {
        "unit": "full wire-contract-native + native-errors + "
                "native-handle-balance sweep",
        "files": len(files),
        "source_lines": total_lines,
        "sweep_s": round(best, 4),
        "files_per_sec": round(len(files) / best, 1),
        "lines_per_sec": round(total_lines / best, 1),
        "findings": len(findings),
    }


def _bench_exception_flow() -> dict:
    """Exception-flow tier cost: the whole-tree may-throw fixpoint
    (call-graph build + summary propagation) wall time, the finding
    counts of the two checks it feeds (0 = every handle and lock
    obligation is exception-safe in-tree), and the determinism proof —
    two independent runs must produce identical finding ids."""
    from brpc_tpu.analysis import callgraph, lint

    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "brpc_tpu")
    if not os.path.isdir(pkg):
        return {"skipped": "no brpc_tpu tree next to this script"}
    import ast as _ast
    paths = sorted(
        os.path.join(dp, fn)
        for dp, _dirs, fns in os.walk(pkg)
        for fn in fns if fn.endswith(".py"))
    files = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            files.append((p, _ast.parse(f.read())))
    repeats = 3
    best_build = best_fix = float("inf")
    n_nodes = n_proven = 0
    for _ in range(repeats):
        t0 = time.perf_counter()
        graph = callgraph.build_callgraph(files)
        t1 = time.perf_counter()
        summaries = graph.compute_throws()
        t2 = time.perf_counter()
        best_build = min(best_build, t1 - t0)
        best_fix = min(best_fix, t2 - t1)
        n_nodes = len(summaries)
        n_proven = sum(1 for s in summaries.values()
                       if s.may_throw and s.confidence == "high")
    checks = ["exception-flow", "lock-exception-safety"]
    run1 = lint.run_lint([pkg], checks=checks)
    run2 = lint.run_lint([pkg], checks=checks)
    return {
        "unit": "whole-tree may-throw fixpoint (build + propagate)",
        "functions": n_nodes,
        "proven_may_throw": n_proven,
        "build_s": round(best_build, 4),
        "fixpoint_s": round(best_fix, 4),
        "within_budget_5s": (best_build + best_fix) < 5.0,
        "exception_flow_findings": sum(
            1 for f in run1 if f.check == "exception-flow"),
        "lock_exception_safety_findings": sum(
            1 for f in run1 if f.check == "lock-exception-safety"),
        "deterministic_ids": [f.id for f in run1] == [f.id for f in run2],
    }


def _bench_fuzz() -> dict:
    """Fuzz throughput per parser (execs/sec, memcheck off — the raw
    mutation+parse loop): how much hostile-input coverage one core buys
    per second, and the deterministic proof the seeded run stays green
    at bench scale too."""
    report = wire_fuzz.run(seed=0, iters=2000, memcheck=False)
    out = {
        "unit": "execs/sec per parser (seed 0, 2000 iters, memcheck "
                "off)",
        "ok": report["ok"],
        "failures": len(report["failures"]),
        "per_parser": {name: stats["execs_per_sec"]
                       for name, stats in report["targets"].items()},
    }
    total = sum(stats["execs"] for stats in report["targets"].values())
    out["total_execs"] = total
    return out


def main() -> dict:
    race.set_enabled(None)
    os.environ.pop("BRPC_TPU_RACECHECK", None)
    os.environ.pop("BRPC_TPU_HANDLECHECK", None)

    plain = threading.Lock()
    off = race.checked_lock("bench.off")
    race.set_enabled(True)
    on = race.checked_lock("bench.on")
    sampled = race.checked_lock("bench.sampled")
    race.set_enabled(None)

    n = 200_000
    sample_n = 64
    plain_ns = _per_op_ns(_acquire_release_loop(plain), n)
    off_ns = _per_op_ns(_acquire_release_loop(off), n)
    on_ns = _per_op_ns(_acquire_release_loop(on), n // 10)
    race.set_sample(sample_n)
    try:
        sampled_ns = _per_op_ns(_acquire_release_loop(sampled), n // 10)
    finally:
        race.set_sample(None)

    result = {
        "metric": "checked_lock_overhead",
        "unit": "ns/op (acquire+release)",
        "threading_lock_ns": round(plain_ns, 1),
        "checked_lock_off_ns": round(off_ns, 1),
        "checked_lock_on_ns": round(on_ns, 1),
        "checked_lock_sampled_ns": round(sampled_ns, 1),
        "racecheck_sample_every": sample_n,
        "sampled_over_full_ratio": round(sampled_ns / on_ns, 4),
        "sampled_within_one_fifth_of_full": sampled_ns <= on_ns / 5,
        "off_is_plain_lock_type": type(off) is type(plain),
        "off_over_plain_ratio": round(off_ns / plain_ns, 3),
        "with_stmt_plain_ns": round(_per_op_ns(_with_loop(plain), n), 1),
        "with_stmt_off_ns": round(_per_op_ns(_with_loop(off), n), 1),
        "ops_per_measurement": n,
        "handle_ledger": _bench_handles(),
        "fuzz": _bench_fuzz(),
        "native_extract": _bench_native_extract(),
        "exception_flow": _bench_exception_flow(),
    }

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_analysis.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result, indent=2))
    return result


if __name__ == "__main__":
    main()
