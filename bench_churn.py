#!/usr/bin/env python3
"""Long-running churn bench: the self-driving-elasticity acceptance
workload (ISSUE 13).

One continuous scenario against a quorum-replicated PS fabric
(2 shards x 3 replicas, majority-ack writes) with a live
:class:`brpc_tpu.rebalance.Rebalancer` in the loop and NO operator
anywhere:

- press-driven sustained load (``press.build_ops`` arrival schedules +
  zipf key draws executed through the scheme-aware client) with a
  single exact-arithmetic writer;
- a kill DURING BOOTSTRAP (the primary dies right after the first
  quorum-acked write — the window the legacy connected-only barrier
  lost writes in);
- a HIGH-load phase the rebalancer answers with an autonomous 2→4
  split, a primary kill + revival the fabric answers with failover and
  an autonomous FAILBACK, and a LOW-load phase answered with an
  autonomous 4→2 merge;
- throughout: availability over every op (reads and writes), and at
  the end the exact zero-lost-acked-update ledger — final tables must
  equal the seed tables minus exactly one ``GRAD_VALUE`` per acked
  occurrence, replayed with the servers' own float order.

Emits ONE JSON line and refreshes BENCH_churn.json.  Degrades to
{"skipped": ...} without the native core.

``--raw`` measures real multi-core behavior instead of the 1-core
sizing: the fiber pool scales to the host's cores and the reader
count scales with them (same per-reader rates), so availability and
the autonomous split/merge/failback run under genuinely parallel
load.  Raw results go to BENCH_churn_raw.json.
"""

import json
import os
import sys
import threading
import time

ROOT = os.path.dirname(os.path.abspath(__file__))

RAW = "--raw" in sys.argv[1:]

# The fiber worker pool is PROCESS-GLOBAL (cpp/fiber TaskControl): on a
# 1-core host it defaults to 4 workers shared by every in-process
# server.  This scenario runs up to 18 servers whose handlers hold a
# worker through quorum ack barriers — 4 workers starve into a timeout
# spiral.  The waits sleep (no CPU), so a wider pool is pure headroom.
# Raw mode sizes the pool to the host instead of the 1-core constant.
os.environ.setdefault(
    "BRT_WORKERS",
    str(max(16, 4 * (os.cpu_count() or 1))) if RAW else "16")

VOCAB, DIM = 512, 8
REPLICAS = 3
WRITE_BATCH = 32
SEED = 42
AVAIL_TARGET = 0.999
#: reader threads: fixed on the 1-core sizing; scales with cores (same
#: per-reader rate) in raw mode so aggregate load exercises real
#: parallelism
N_READERS = 3 * (os.cpu_count() or 1) if RAW else 3


def main() -> int:  # noqa: C901 — one scenario, phases inline
    try:
        from brpc_tpu import rpc
        if not rpc.native_core_available():
            print(json.dumps({"skipped": "native core unavailable"}))
            return 0
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        print(json.dumps({"skipped": f"{type(e).__name__}: {e}"[:200]}))
        return 0
    import numpy as np

    from brpc_tpu import fault, obs, press, resilience
    from brpc_tpu.naming import (NamingClient, PartitionScheme,
                                 ReplicaSet, parse_schemes)
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding
    from brpc_tpu.rebalance import (RebalanceOptions, RebalancePolicy,
                                    Rebalancer)

    obs.set_enabled(True)
    t_bench0 = time.monotonic()
    GRAD = press.GRAD_VALUE

    # -- cluster bring-up --------------------------------------------------
    reg_server = rpc.Server()
    reg_server.add_naming_registry()
    reg_addr = f"127.0.0.1:{reg_server.start('127.0.0.1:0')}"
    nc = NamingClient(reg_addr)

    groups = {}          # scheme version -> [PsShardServer]
    parked = {}          # retired, awaiting the deferred close
    closed_groups = []

    def spawn_group(version: int, num_shards: int,
                    importing: bool) -> PartitionScheme:
        for sv in groups.pop(version, []):
            sv.close()   # a stillborn earlier attempt at this version
        servers = []
        sets = []
        for s in range(num_shards):
            row = [PsShardServer(VOCAB, DIM, s, num_shards, lr=1.0,
                                 seed=SEED, importing=importing,
                                 scheme_version=version)
                   for _ in range(REPLICAS)]
            rs = ReplicaSet(tuple(sv.address for sv in row), primary=0)
            for r, sv in enumerate(row):
                sv.configure_replication(rs, r)   # auto: majority=2
                nc.register("ps", sv.address, ttl_ms=1000,
                            tag_fn=sv.claim_tag)
            servers.extend(row)
            sets.append(rs)
        groups[version] = servers
        return PartitionScheme(version, tuple(sets))

    def close_group(scheme: PartitionScheme) -> None:
        """Retirement close with a GRACE period: clients learn of the
        retirement through the registry watch — closing the old
        servers on the same instant races that ingest (a writer one
        beat behind would hit connection-refused instead of a clean
        redirect).  The deferred close is the operational equivalent
        of a decommission delay."""
        servers = groups.pop(scheme.version, [])
        parked[scheme.version] = servers
        closed_groups.append(scheme.version)

        def _close_later():
            time.sleep(3.0)
            for sv in parked.pop(scheme.version, []):
                sv.close()

        threading.Thread(target=_close_later, daemon=True).start()

    sc1 = spawn_group(1, 2, importing=False)
    from brpc_tpu.naming import publish_scheme
    publish_scheme(nc, "ps", sc1)
    init_tables = np.concatenate(
        [groups[1][s * REPLICAS].table.copy() for s in range(2)])

    # Thresholds sized to the phase rates below ON A 1-CORE HOST:
    # the per-shard signal is reads + applied write batches, and the
    # ~12/s writer touches every shard each batch, so the write floor
    # (~12/s/shard) sits between merge_qps and split_qps.
    policy = RebalancePolicy(RebalanceOptions(
        split_qps=30.0, merge_qps=15.0, sustain_s=0.4,
        min_interval_s=2.0, max_shards=4, min_shards=2,
        failback_sustain_s=0.2))
    reb = Rebalancer(reg_addr, "ps", VOCAB, policy=policy,
                     provisioner=lambda v, n: spawn_group(
                         v, n, importing=True),
                     on_retired=close_group, interval_ms=250.0,
                     timeout_ms=1000, migrate_deadline_s=60.0,
                     drain_deadline_s=10.0)

    retry = resilience.RetryPolicy(
        max_attempts=6,
        backoff=resilience.Backoff(base_ms=2, max_ms=50),
        attempt_timeout_ms=800)
    emb = RemoteEmbedding.from_registry(reg_addr, "ps", VOCAB, DIM,
                                        timeout_ms=4000, watch=True,
                                        retry=retry)

    # -- load engine -------------------------------------------------------
    ok_ops = [0]
    failed_ops = []
    counts = np.zeros(VOCAB, np.int64)     # acked apply occurrences
    tainted = []                           # a failed write = ambiguous
    stop = threading.Event()
    read_qps = [0.0]                       # phase-controlled
    rng = np.random.default_rng(SEED)

    def writer() -> None:
        """One sequential exact-ledger writer: ~25 batches/s, every
        acked batch recorded per id occurrence."""
        wrng = np.random.default_rng(SEED + 1)
        while not stop.is_set():
            ids = wrng.integers(0, VOCAB, WRITE_BATCH).astype(np.int32)
            grads = np.full((WRITE_BATCH, DIM), GRAD, np.float32)
            try:
                emb.apply_gradients(ids, grads)
            except Exception as e:  # noqa: BLE001 — the verdict
                failed_ops.append(f"write:{e!r}"[:160])
                tainted.append(True)
                time.sleep(0.05)
                continue
            np.add.at(counts, ids, 1)
            ok_ops[0] += 1
            time.sleep(0.08)

    def reader(k: int) -> None:
        """Press-schedule readers: each runs the zipf key draws of a
        press scenario at the CURRENT phase rate (open-ish loop: the
        pace follows read_qps, the draws stay seeded)."""
        sc = press.Scenario(duration_s=3600.0, qps=1.0, batch=16,
                            zipf_s=1.1, seed=SEED + 10 + k)
        keys = press.zipf_weights(VOCAB, sc.zipf_s)
        rrng = np.random.default_rng(sc.seed)
        while not stop.is_set():
            rate = read_qps[0]
            if rate <= 0:
                time.sleep(0.02)
                continue
            ids = rrng.choice(VOCAB, size=sc.batch,
                              p=keys).astype(np.int32)
            try:
                emb.lookup(np.sort(ids))
            except Exception as e:  # noqa: BLE001 — the verdict
                failed_ops.append(f"read:{e!r}"[:160])
                time.sleep(0.02)
                continue
            ok_ops[0] += 1
            time.sleep(1.0 / rate)

    timeline = []

    def monitor() -> None:
        last = [0, 0]
        while not stop.is_set():
            time.sleep(2.0)
            try:
                with emb._view_mu:
                    views = [(v.version, v.state) for v in emb._views]
            except Exception:  # noqa: BLE001 — sampling only
                views = ["?"]
            nf = len(failed_ops)
            timeline.append(
                f"t+{time.monotonic() - t_bench0:.0f}s ok={ok_ops[0]} "
                f"(+{ok_ops[0] - last[0]}) fail={nf} (+{nf - last[1]}) "
                f"views={views}")
            last = [ok_ops[0], nf]

    threads = [threading.Thread(target=writer, daemon=True)]
    threads += [threading.Thread(target=reader, args=(k,),
                                 daemon=True) for k in range(N_READERS)]
    threads += [threading.Thread(target=monitor, daemon=True)]

    phases = []
    kills = []

    def active_version() -> int:
        nodes, _ = nc.list("ps")
        schemes = parse_schemes(nodes)
        act = [sc for sc in schemes.values() if sc.state == "active"]
        return max((sc.version for sc in act), default=0)

    def wait_for(cond, what: str, deadline_s: float) -> bool:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if cond():
                return True
            time.sleep(0.1)
        failed_ops.append(f"phase-timeout:{what}")
        return False

    def kill(addr: str) -> None:
        kills.append(addr)
        fault.install(fault.FaultPlan(fault.kill_rules(addr),
                                      seed=SEED))
        rpc.debug_fail_connections(addr)

    ok = True
    out = {}
    try:
        # -- phase 0: bootstrap kill --------------------------------------
        t0 = time.monotonic()
        ids0 = np.arange(WRITE_BATCH, dtype=np.int32)
        emb.apply_gradients(ids0, np.full((WRITE_BATCH, DIM), GRAD,
                                          np.float32))
        np.add.at(counts, ids0, 1)
        ok_ops[0] += 1
        boot_primary = groups[1][0].address   # shard 0 replica 0
        kill(boot_primary)
        # the acked write must survive the primary: the next write
        # fails over through the majority and lands on a quorum holder
        emb.apply_gradients(ids0, np.full((WRITE_BATCH, DIM), GRAD,
                                          np.float32))
        np.add.at(counts, ids0, 1)
        ok_ops[0] += 1
        phases.append({"phase": "bootstrap_kill",
                       "killed": boot_primary,
                       "wall_s": round(time.monotonic() - t0, 2)})
        fault.clear()    # the zombie rejoins as a backup via fencing

        for t in threads:
            t.start()
        reb.start()

        # -- phase 1: high load -> autonomous split 2->4 ------------------
        t0 = time.monotonic()
        read_qps[0] = 17.0     # x3 readers + ~12/s writes: per-shard
        #                        ~37/s on 2 shards, above split_qps
        split_ok = wait_for(lambda: active_version() >= 2,
                            "autonomous split", 120.0)
        if split_ok:
            time.sleep(10.0)   # sustained traffic on the new topology
        phases.append({"phase": "high_load_split", "ok": split_ok,
                       "active_version": active_version(),
                       "wall_s": round(time.monotonic() - t0, 2)})
        ok &= split_ok

        # -- phase 2: primary kill -> failover -> revival -> failback -----
        t0 = time.monotonic()
        v2_servers = groups.get(2, [])
        victim = v2_servers[0] if v2_servers else None
        failback_ok = False
        if split_ok and victim is not None:
            fb0 = int(obs.counter("ps_failbacks").get_value())
            kill(victim.address)
            promoted = wait_for(
                lambda: any(sv.is_primary
                            for sv in v2_servers[1:REPLICAS]),
                "failover promotion", 30.0)
            # revive: the zombie re-fences into a backup, catches up,
            # and the rebalancer promotes it back on its own
            fault.clear()
            failback_ok = promoted and wait_for(
                lambda: int(obs.counter("ps_failbacks").get_value())
                > fb0 and victim.is_primary,
                "autonomous failback", 45.0)
        if failback_ok:
            time.sleep(5.0)    # steady traffic behind the restored
            #                    primary before the load drops
        phases.append({"phase": "kill_revive_failback",
                       "ok": failback_ok,
                       "wall_s": round(time.monotonic() - t0, 2)})
        ok &= failback_ok

        # -- phase 3: low load -> autonomous merge 4->2 -------------------
        t0 = time.monotonic()
        read_qps[0] = 0.3      # per-shard ~13/s (the write floor),
        #                        inside the merge band on 4 shards
        merge_ok = split_ok and wait_for(
            lambda: active_version() >= 3, "autonomous merge", 120.0)
        if merge_ok:
            time.sleep(10.0)   # the merged topology carries the tail
        phases.append({"phase": "low_load_merge", "ok": merge_ok,
                       "active_version": active_version(),
                       "wall_s": round(time.monotonic() - t0, 2)})
        ok &= merge_ok

        # -- wind down + ledger -------------------------------------------
        stop.set()
        for t in threads:
            t.join(timeout=15)
        reb.stop()

        n_failed = len([f for f in failed_ops
                        if not f.startswith("phase-timeout")])
        total_ops = ok_ops[0] + n_failed
        availability = ok_ops[0] / total_ops if total_ops else 0.0

        # exact replay: every acked occurrence subtracts one GRAD, in
        # the same per-id float order the servers applied
        expect = init_tables.copy()
        for step in range(int(counts.max())):
            expect[counts > step] -= np.float32(GRAD)
        final_version = active_version()
        final_scheme_servers = groups.get(final_version, [])
        nsh = len(final_scheme_servers) // REPLICAS
        ledger_exact = False
        if not tainted and nsh:
            finals = []
            for s in range(nsh):
                row = final_scheme_servers[s * REPLICAS:
                                           (s + 1) * REPLICAS]
                prim = next((sv for sv in row if sv.is_primary),
                            row[0])
                finals.append(prim.table)
            got = np.concatenate(finals)
            ledger_exact = bool(np.array_equal(got, expect))

        out = {
            "metric": "churn_availability",
            "value": round(availability, 5),
            "unit": "fraction",
            "raw": RAW,
            "cpu_count": os.cpu_count(),
            "readers": N_READERS,
            "ops": total_ops,
            "ok_ops": ok_ops[0],
            "failed_ops": failed_ops[:20],
            "kills": kills,
            "phases": phases,
            "splits": int(obs.counter(
                "ps_rebalance_splits").get_value()),
            "merges": int(obs.counter(
                "ps_rebalance_merges").get_value()),
            "failbacks": int(obs.counter("ps_failbacks").get_value()),
            "promotions": int(obs.counter(
                "ps_replica_promotions").get_value()),
            "redrives": int(obs.counter(
                "ps_migration_redrives").get_value()),
            "rebalance_errors": int(obs.counter(
                "ps_rebalance_errors").get_value()),
            "rebalance_error_detail": reb.errors[:6],
            "rebalance_log": reb.log,
            "timeline": timeline,
            "final_active_version": final_version,
            "ledger_exact": ledger_exact,
            "ledger_tainted": bool(tainted),
            "criteria": {
                "availability_ge_0p999": availability >= AVAIL_TARGET,
                "autonomous_split": bool(int(obs.counter(
                    "ps_rebalance_splits").get_value()) >= 1),
                "autonomous_merge": bool(int(obs.counter(
                    "ps_rebalance_merges").get_value()) >= 1),
                "autonomous_failback": bool(int(obs.counter(
                    "ps_failbacks").get_value()) >= 1),
                "bootstrap_kill_lossless_ledger": ledger_exact,
            },
            "wall_s": round(time.monotonic() - t_bench0, 2),
        }
        out["ok"] = bool(ok and all(out["criteria"].values()))
    finally:
        stop.set()
        fault.clear()
        try:
            reb.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        emb.close()
        nc.close()
        for servers in (list(groups.values())
                        + list(parked.values())):
            for sv in servers:
                try:
                    sv.close()
                except Exception:  # noqa: BLE001 — deferred-close race
                    pass
        reg_server.close()

    with open(os.path.join(
            ROOT, "BENCH_churn_raw.json" if RAW else "BENCH_churn.json"),
            "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
