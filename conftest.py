import os
import sys

# Make `brpc_tpu` and `__graft_entry__` importable under a bare `pytest`
# invocation (no packaging yet).
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
