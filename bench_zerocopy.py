#!/usr/bin/env python3
"""Zero-copy buffer currency benchmark (brt_iobuf): the copy path vs
the borrow path, A/B in ONE run.

Cells (each measured both ways, same process, same wall-clock windows):

- large-payload echo GB/s — bytes path (request memcpy'd into the
  native chain, response malloc+copy_out'd back) vs iobuf path
  (request payload borrowed via ``append_pinned``, response adopted as
  a native block list, never materialized);
- stream-push throughput — the PS gradient-stream framing: per-frame
  copied ``Stream.write`` (header+body concat, then a native memcpy)
  vs ``Stream.writev`` of a borrowed-body iobuf frame.  Each cell runs
  on a fresh stream and WAITS for the sink to drain before the next
  starts, so no cell inherits the previous one's back-pressure debt;
  best-of-3 per mode is the recorded rate (single-core scheduling
  jitter is large relative to the gap);
- ps_push_gradients — the same switch end-to-end through
  ``RemoteEmbedding.push_gradients`` (``set_zerocopy`` is the PS
  tier's own toggle).  Report-only: the in-process shard's consume
  side (frame copy + numpy apply, identical both modes) shares this
  host's one core, so the framing savings are diluted here;
- 16-byte echo qps — the small-payload floor.  Report-only: at 16
  bytes the borrow path's per-call handle lifecycle costs more than
  the memcpys it saves; the cell documents the crossover, it does not
  claim a win;
- bytes-copied-per-request — the ``rpc_bytes_copied`` obs counter
  differenced across each echo loop.  The borrow path must HALVE the
  ledger: the residual is the server trampoline materializing the
  request for the Python handler, which both modes pay.

Emits ONE JSON line and refreshes BENCH_zerocopy.json.  Every loop is
wall-clock bounded (the bench.py child deadline guards the whole run);
degrades to {"skipped": ...} without the native core.
"""

import json
import os
import time

ROOT = os.path.dirname(os.path.abspath(__file__))

LARGE = 4 * 1024 * 1024     # large-payload echo body
SMALL = 16                  # small-payload echo body
CELL_S = 2.0                # per-cell measurement window
FRAME = 1024 * 1024         # stream-push frame body
STREAM_WIN = 16 << 20       # stream unconsumed-bytes window
STREAM_TRIALS = 3           # best-of-N stream cells per mode
PUSH_VOCAB, PUSH_DIM, PUSH_BATCH = 8192, 512, 512
DRAIN_S = 30.0              # sink catch-up deadline between cells


def _copied_per_req(obs, calls, c0):
    copied = int(obs.counter("rpc_bytes_copied").get_value()) - c0
    return round(copied / max(calls, 1), 1)


def bench_zerocopy() -> dict:
    import numpy as np

    from brpc_tpu import obs, rpc
    from brpc_tpu import ps_remote
    from brpc_tpu.naming import PartitionScheme, ReplicaSet
    from brpc_tpu.ps_remote import (PsShardServer, RemoteEmbedding,
                                    _pack_stream_frame,
                                    _pack_stream_frame_iobuf)

    obs.set_enabled(True)
    out = {"metric": "zerocopy_currency",
           "cpu_count": os.cpu_count(),
           "large_payload": LARGE, "small_payload": SMALL,
           "stream_frame": FRAME, "cell_s": CELL_S}

    # -- echo server: same handler serves both modes -----------------------
    zc_respond = [False]
    srv = rpc.Server()

    def echo(method, request):
        if zc_respond[0]:
            # force_iobuf: this bench measures the borrow path on BOTH
            # sides of the IOBUF_MIN_BYTES engagement floor (the 16B
            # cell IS the below-floor cost probe) — without it the
            # small cell would silently measure the bytes twin the
            # production path auto-routes to.
            rsp = rpc.IOBuf(force_iobuf=True)
            rsp.append_pinned(request)   # borrow the request bytes
            return rsp
        return request
    srv.add_service("Echo", echo)
    port = srv.start("127.0.0.1:0")
    ch = rpc.Channel(f"127.0.0.1:{port}", timeout_ms=10_000)

    def echo_bytes(payload):
        calls = 0
        c0 = int(obs.counter("rpc_bytes_copied").get_value())
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < CELL_S:
            rsp = ch.call("Echo", "Echo", payload)
            assert len(rsp) == len(payload)
            calls += 1
        wall = time.perf_counter() - t0
        return calls, wall, _copied_per_req(obs, calls, c0)

    def echo_iobuf(payload):
        calls = 0
        c0 = int(obs.counter("rpc_bytes_copied").get_value())
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < CELL_S:
            req = rpc.IOBuf(force_iobuf=True)   # probe below the floor too
            req.append_pinned(payload)
            rsp = ch.call("Echo", "Echo", req)
            try:
                # brt_iobuf_size: no materialization — the borrow contract
                assert len(rsp) == len(payload)
            finally:
                rsp.close()
                req.close()
            calls += 1
        wall = time.perf_counter() - t0
        return calls, wall, _copied_per_req(obs, calls, c0)

    def gbps(calls, wall, payload):
        # request + response bytes over the wall window
        return round(2.0 * len(payload) * calls / wall / 1e9, 3)

    try:
        big = np.random.default_rng(7).bytes(LARGE)
        small = b"x" * SMALL

        # warmup: connections, fiber pool, first-call laziness
        for _ in range(20):
            ch.call("Echo", "Echo", small)

        zc_respond[0] = False
        calls, wall, cop = echo_bytes(big)
        before_large = {"gbps": gbps(calls, wall, big), "calls": calls,
                        "copied_bytes_per_req": cop}
        zc_respond[0] = True
        calls, wall, cop = echo_iobuf(big)
        after_large = {"gbps": gbps(calls, wall, big), "calls": calls,
                       "copied_bytes_per_req": cop}

        zc_respond[0] = False
        calls, wall, cop = echo_bytes(small)
        before_small = {"qps": round(calls / wall, 1), "calls": calls,
                        "copied_bytes_per_req": cop}
        zc_respond[0] = True
        calls, wall, cop = echo_iobuf(small)
        after_small = {"qps": round(calls / wall, 1), "calls": calls,
                       "copied_bytes_per_req": cop}

        out["echo_large"] = {
            "before": before_large, "after": after_large,
            "speedup": round(after_large["gbps"]
                             / max(before_large["gbps"], 1e-9), 3)}
        out["echo_small"] = {
            "before": before_small, "after": after_small,
            "speedup": round(after_small["qps"]
                             / max(before_small["qps"], 1e-9), 3),
            "note": "report-only: 16B is below the borrow crossover"}
    finally:
        ch.close()
        srv.close()

    # -- stream push: per-frame copied write vs writev'd borrowed frame ----
    class _Sink:
        def __init__(self):
            self.nbytes = 0

        def on_data(self, data):
            self.nbytes += len(data)

        def on_closed(self):
            pass

    sink = _Sink()
    ssrv = rpc.Server()

    def _accept_push(method, request, accept):
        accept(sink, max_buf_size=STREAM_WIN)
        return b"ok"
    ssrv.add_stream_handler("Push", _accept_push)
    sport = ssrv.start("127.0.0.1:0")
    sch = rpc.Channel(f"127.0.0.1:{sport}", timeout_ms=10_000)
    body = np.random.default_rng(3).bytes(FRAME)
    hdr_len = len(_pack_stream_frame(0, 0, 0, b""))
    fed = [0]                 # total bytes handed to the stream layer

    def stream_cell(zc):
        st = sch.stream("Push", "Open", b"", max_buf_size=STREAM_WIN)
        try:
            sent = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < CELL_S:
                if zc:
                    io = _pack_stream_frame_iobuf(sent, 0, 0, body)
                    try:
                        st.writev([io])
                    finally:
                        io.close()
                else:
                    st.write(_pack_stream_frame(sent, 0, 0, body))
                sent += 1
            wall = time.perf_counter() - t0
            fed[0] += sent * (FRAME + hdr_len)
            # drain: the next cell must not start against this cell's
            # back-pressure debt
            deadline = time.time() + DRAIN_S
            while sink.nbytes < fed[0] and time.time() < deadline:
                time.sleep(0.005)
            return round(sent * FRAME / wall / 1e6, 1)
        finally:
            st.close()

    try:
        runs = {"before": [], "after": []}
        for _ in range(STREAM_TRIALS):
            runs["before"].append(stream_cell(False))
            runs["after"].append(stream_cell(True))
        before_mbps = max(runs["before"])
        after_mbps = max(runs["after"])
        out["stream_push"] = {
            "before": {"mbps": before_mbps, "runs": runs["before"]},
            "after": {"mbps": after_mbps, "runs": runs["after"]},
            "speedup": round(after_mbps / max(before_mbps, 1e-9), 3)}
    finally:
        sch.close()
        ssrv.close()

    # -- end-to-end push_gradients: the PS tier's own switch (report) ------
    shard = PsShardServer(PUSH_VOCAB, PUSH_DIM, 0, 1, lr=1.0, stream=True)
    sc = PartitionScheme(0, (ReplicaSet.of(shard.address),))
    emb = RemoteEmbedding([sc], PUSH_VOCAB, PUSH_DIM, timeout_ms=10_000)
    ids = np.arange(PUSH_BATCH, dtype=np.int32)
    grads = np.full((PUSH_BATCH, PUSH_DIM), 0.5, np.float32)
    body_bytes = PUSH_BATCH * (4 + 4 * PUSH_DIM) + 4

    def push_cell(zc):
        prev = ps_remote.set_zerocopy(zc)
        try:
            emb.push_gradients(ids, grads)   # open the stream outside
            emb.flush_gradients()            # the measured window
            pushes = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < CELL_S:
                emb.push_gradients(ids, grads)
                pushes += 1
            emb.flush_gradients()            # every counted push acked
            wall = time.perf_counter() - t0
        finally:
            ps_remote.set_zerocopy(prev)
        return {"pushes": pushes,
                "rows_per_s": round(pushes * PUSH_BATCH / wall, 1),
                "mbps": round(pushes * body_bytes / wall / 1e6, 2)}

    try:
        before_push = push_cell(False)
        after_push = push_cell(True)
        out["ps_push_gradients"] = {
            "before": before_push, "after": after_push,
            "speedup": round(after_push["mbps"]
                             / max(before_push["mbps"], 1e-9), 3),
            "note": "report-only: the in-process shard's consume side "
                    "(frame copy + numpy apply) is identical both modes "
                    "and shares this host's core"}
    finally:
        emb.close()
        shard.close()

    out["criteria"] = {
        "echo_large_ge_1p3x": out["echo_large"]["speedup"] >= 1.3,
        "stream_push_ge_1p3x": out["stream_push"]["speedup"] >= 1.3,
        # the borrow path keeps exactly one counted copy: the server
        # trampoline materializing the request bytes for the Python
        # handler (paid by both modes)
        "copy_ledger_halved":
            out["echo_large"]["after"]["copied_bytes_per_req"]
            <= 0.55 * out["echo_large"]["before"]["copied_bytes_per_req"],
    }
    out["ok"] = bool(all(out["criteria"].values()))
    return out


def main() -> int:
    out_path = os.path.join(ROOT, "BENCH_zerocopy.json")
    try:
        from brpc_tpu import rpc

        if not rpc.native_core_available():
            result = {"metric": "zerocopy_currency",
                      "skipped": "native core unavailable"}
        else:
            result = bench_zerocopy()
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        result = {"metric": "zerocopy_currency",
                  "skipped": f"{type(e).__name__}: {e}"[:300]}
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
