#!/usr/bin/env python3
"""Fault-tolerance benchmark: backup requests vs a slow shard, breaker
availability vs a flapping shard — all failures INJECTED by the seeded
fault plan (brpc_tpu.fault), so every run replays the same schedule.

Run BY bench.py in a deadline-guarded child (same pattern as
bench_ps.py); standalone `python bench_fault.py` works too.  Emits
BENCH_fault.json and prints ONE JSON object.  Without the native core it
degrades to {"skipped": ...}.

What it measures (loopback, 4 CPU shards, obs ON — the counters ARE part
of what is being verified):

  slow_shard  — shard 2's Lookup handler sleeps 30ms on 5% of calls
                (deterministic schedule).  One multi-shard lookup batch,
                no-hedge vs backup_ms=8.  Hedging math: p99 without the
                hedge IS the delay (5% > 1%); with it, only
                both-attempts-slow batches stay slow (0.25% < 1%), so
                p99 collapses to the fast path and every losing attempt
                is cancelled (counter-verified).
  flapping    — shard 2 alternates down/up phases (down = 70% of calls
                "dropped", burning the attempt timeout — wall-time
                phases; decisions within a phase stay seeded).  Batches
                under
                three configs: bare, retry (2 extra attempts + budget),
                retry+breaker+prober (EMA isolation, fail-fast, health
                revival).  Retry buys availability (it rescues partial
                drops); the breaker buys back throughput and bounds
                error latency (fail in microseconds, not timeouts) while
                the probe revives the shard for the up phase.
  replication — the SAME flapping scenario with replicas=2 and the
                redirecting breaker: reads redirect to the live replica,
                writes fail over via fenced promotion, availability goes
                ~0.24 -> ~1.0 at sub-ms latency with byte-identical
                replica tables and zero lost acked updates at the end.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def _counters(*names):
    from brpc_tpu import obs

    return {n: int(obs.counter(n).get_value()) for n in names}


def bench_slow_shard(nshards: int = 4, vocab: int = 4096, dim: int = 32,
                     batch: int = 512, rounds: int = 400) -> dict:
    from brpc_tpu import fault, obs
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

    servers = [PsShardServer(vocab, dim, i, nshards)
               for i in range(nshards)]
    addrs = [s.address for s in servers]
    ids = np.arange(batch, dtype=np.int32) * (vocab // batch)  # all shards
    out: dict = {"delay_ms": 30, "delay_probability": 0.05,
                 "backup_ms": 8, "rounds": rounds}
    try:
        for mode, backup_ms in (("no_backup", None), ("backup", 8)):
            fault.install(fault.FaultPlan([fault.FaultRule(
                action="delay", side="server", service="Ps",
                method="Lookup", endpoint=addrs[2], delay_ms=30,
                probability=0.05)], seed=42))
            obs.reset_fabric_vars()
            emb = RemoteEmbedding(addrs, vocab, dim, timeout_ms=60000,
                                  backup_ms=backup_ms)
            lat = []
            try:
                emb.lookup(ids)  # warm
                for _ in range(rounds):
                    t0 = time.perf_counter_ns()
                    emb.lookup(ids)
                    lat.append((time.perf_counter_ns() - t0) / 1e6)
            finally:
                emb.close()
                fault.clear()
            lat.sort()
            out[mode] = {
                "mean_ms": round(sum(lat) / len(lat), 3),
                "p50_ms": round(_pct(lat, 0.50), 3),
                "p90_ms": round(_pct(lat, 0.90), 3),
                "p99_ms": round(_pct(lat, 0.99), 3),
                **_counters("rpc_backup_fired", "rpc_backup_wins",
                            "rpc_cancels"),
            }
    finally:
        for s in servers:
            s.close()
    out["p99_ratio_backup_over_none"] = round(
        out["backup"]["p99_ms"] / max(out["no_backup"]["p99_ms"], 1e-9), 3)
    return out


def bench_flapping(nshards: int = 4, vocab: int = 4096, dim: int = 32,
                   batch: int = 512, secs: float = 2.0,
                   phase_ms: float = 300.0) -> dict:
    from brpc_tpu import fault, obs, resilience, rpc
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

    servers = [PsShardServer(vocab, dim, i, nshards)
               for i in range(nshards)]
    addrs = [s.address for s in servers]
    ids = np.arange(batch, dtype=np.int32) * (vocab // batch)
    # attempt cap: a black-holed (dropped) attempt costs <=60ms, leaving
    # budget for the retries the deadline was supposed to buy
    retry = resilience.RetryPolicy(
        max_attempts=3, backoff=resilience.Backoff(base_ms=2, max_ms=10),
        attempt_timeout_ms=60)

    def breaker_cfg():
        return {"retry": retry, "deadline_ms": 1000,
                "breakers": resilience.BreakerRegistry(
                    resilience.BreakerOptions(
                        short_window=8, min_samples=2,
                        min_isolation_ms=100)),
                "health_check": True, "health_interval_ms": 20}

    down_plan = fault.FaultPlan([fault.FaultRule(
        action="drop", side="client", endpoint=addrs[2],
        delay_ms=150, probability=0.7)], seed=7)
    configs = {
        "bare": lambda: {},
        "retry": lambda: {"retry": retry, "deadline_ms": 1000},
        "retry_breaker_probe": breaker_cfg,
    }
    out: dict = {"down_drop_probability": 0.7, "drop_cost_ms": 150,
                 "phase_ms": phase_ms, "secs": secs}
    try:
        for name, make_kw in configs.items():
            obs.reset_fabric_vars()
            emb = RemoteEmbedding(addrs, vocab, dim, timeout_ms=60000,
                                  **make_kw())
            ok = fail = 0
            ok_lat, err_lat = [], []
            try:
                t_start = time.monotonic()
                t_end = t_start + secs
                while time.monotonic() < t_end:
                    # down/up phases keyed by wall time (shard 2 flaps,
                    # the rest of the fleet stays healthy); the plan's
                    # decisions WITHIN a phase stay seeded/deterministic
                    phase = int((time.monotonic() - t_start) * 1000.0
                                / phase_ms)
                    if phase % 2 == 0:
                        fault.install(down_plan)
                    else:
                        fault.clear()
                    t0 = time.perf_counter_ns()
                    try:
                        emb.lookup(ids)
                        ok += 1
                        ok_lat.append((time.perf_counter_ns() - t0) / 1e6)
                    except rpc.RpcError:
                        fail += 1
                        err_lat.append((time.perf_counter_ns() - t0) / 1e6)
            finally:
                emb.close()
                fault.clear()
            total = ok + fail
            ok_lat.sort()
            out[name] = {
                "batches": total,
                "availability": round(ok / max(total, 1), 4),
                # successful batches per second is the cross-config
                # yardstick: error batches are nearly free under the
                # breaker, so raw batch counts would flatter it
                "ok_per_s": round(ok / secs, 1),
                "ok_mean_ms": round(sum(ok_lat) / len(ok_lat), 3)
                if ok_lat else None,
                "err_mean_ms": round(sum(err_lat) / len(err_lat), 3)
                if err_lat else None,
                **_counters("rpc_retries", "rpc_breaker_open",
                            "rpc_breaker_fastfail",
                            "rpc_breaker_revived"),
            }
    finally:
        for s in servers:
            s.close()
    return out


def bench_replication(nshards: int = 4, vocab: int = 4096, dim: int = 32,
                      batch: int = 512, secs: float = 2.0,
                      phase_ms: float = 300.0) -> dict:
    """The flapping-shard scenario re-run with replicas=2 and the
    redirecting breaker: the SAME down/up phases and drop rule against
    shard 2's boot primary that leave single-owner availability at
    ~0.24, but every row range now has a backup — reads redirect to the
    live replica (latency+inflight score), the first failed write
    promotes the backup with a fencing epoch, and the prober revives the
    flapper back into the read set each up phase.  Availability should
    be ~1.0 at sub-ms mean latency.  Writes ride along every batch with
    exactly-representable deltas; after the flap the block proves ZERO
    lost updates: every ACKED write is present, and primary/backup
    tables are byte-identical after the flush barrier."""
    from brpc_tpu import fault, obs, resilience, rpc
    from brpc_tpu.naming import ReplicaSet
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

    nrep = 2
    servers = [[PsShardServer(vocab, dim, s, nshards, lr=1.0)
                for _ in range(nrep)] for s in range(nshards)]
    sets = []
    for s in range(nshards):
        rs = ReplicaSet(tuple(sv.address for sv in servers[s]),
                        primary=0)
        sets.append(rs)
        for r, sv in enumerate(servers[s]):
            sv.configure_replication(rs, r, timeout_ms=200)
    retry = resilience.RetryPolicy(
        max_attempts=3, backoff=resilience.Backoff(base_ms=2, max_ms=10),
        attempt_timeout_ms=60)
    flap_addr = sets[2].addresses[0]   # shard 2's boot primary flaps
    down_plan = fault.FaultPlan([fault.FaultRule(
        action="drop", side="client", endpoint=flap_addr,
        delay_ms=150, probability=0.7)], seed=7)
    ids = np.arange(batch, dtype=np.int32) * (vocab // batch)
    rows_per = vocab // nshards
    write_ids = np.arange(rows_per, dtype=np.int32) + 2 * rows_per
    delta = np.full((write_ids.size, dim), 0.5, np.float32)  # exact
    out: dict = {"down_drop_probability": 0.7, "drop_cost_ms": 150,
                 "phase_ms": phase_ms, "secs": secs, "replicas": nrep}
    obs.reset_fabric_vars()
    emb = RemoteEmbedding(
        sets, vocab, dim, timeout_ms=60000, retry=retry,
        deadline_ms=1000,
        breakers=resilience.BreakerRegistry(
            resilience.BreakerOptions(short_window=8, min_samples=2,
                                      min_isolation_ms=100),
            redirect=True),
        health_check=True, health_interval_ms=20)
    ok = fail = acked_writes = 0
    ok_lat, err_lat = [], []
    try:
        t_start = time.monotonic()
        t_end = t_start + secs
        while time.monotonic() < t_end:
            phase = int((time.monotonic() - t_start) * 1000.0 / phase_ms)
            if phase % 2 == 0:
                fault.install(down_plan)
            else:
                fault.clear()
            t0 = time.perf_counter_ns()
            try:
                emb.lookup(ids)
                emb.apply_gradients(write_ids, delta)
                ok += 1
                acked_writes += 1
                ok_lat.append((time.perf_counter_ns() - t0) / 1e6)
            except rpc.RpcError:
                fail += 1
                err_lat.append((time.perf_counter_ns() - t0) / 1e6)
        fault.clear()
        # flush barrier on shard 2's CURRENT primary, then exact parity
        cur = sets[2].addresses[emb._primary_idx[2]]
        ch = rpc.Channel(cur, timeout_ms=5000)
        try:
            ch.call("Ps", "Flush", b"")
        finally:
            ch.close()
        # the demoted flapper catches up via the new primary's Sync
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not np.array_equal(
                servers[2][0].table, servers[2][1].table):
            time.sleep(0.02)
        rng = np.random.default_rng(0 + 2)
        expect = (rng.standard_normal((rows_per, dim)) * 0.02
                  ).astype(np.float32)
        for _ in range(acked_writes):
            expect -= np.float32(0.5)   # lr=1.0: one exact step/ack
        parity = bool(np.array_equal(servers[2][0].table,
                                     servers[2][1].table))
        exact = bool(np.array_equal(servers[2][1].table, expect))
        total = ok + fail
        ok_lat.sort()
        out["redirect"] = {
            "batches": total,
            "availability": round(ok / max(total, 1), 4),
            "ok_per_s": round(ok / secs, 1),
            "ok_mean_ms": round(sum(ok_lat) / len(ok_lat), 3)
            if ok_lat else None,
            "ok_p99_ms": round(_pct(ok_lat, 0.99), 3) if ok_lat else None,
            "err_mean_ms": round(sum(err_lat) / len(err_lat), 3)
            if err_lat else None,
            "acked_writes": acked_writes,
            "replica_parity_byte_identical": parity,
            "zero_lost_updates": exact,
            **_counters("rpc_retries", "rpc_breaker_open",
                        "rpc_breaker_redirects", "rpc_breaker_revived",
                        "ps_client_failovers", "ps_client_promotes",
                        "ps_replica_syncs", "ps_replica_frames",
                        "ps_replica_fenced", "ps_replica_demotions"),
        }
    finally:
        fault.clear()
        emb.close()
        for row in servers:
            for sv in row:
                sv.close()
    return out


def main() -> int:
    out_path = os.path.join(ROOT, "BENCH_fault.json")
    result: dict = {"metric": "fault_tolerance",
                    "cpu_count": os.cpu_count()}
    os.environ.setdefault("BRT_WORKERS", str(max(8, os.cpu_count() or 1)))
    try:
        from brpc_tpu import obs, rpc

        if not rpc.native_core_available():
            result = {"metric": "fault_tolerance",
                      "skipped": rpc._load_error or
                      "native core unavailable"}
        else:
            obs.set_enabled(True)  # counters are part of the verdict
            result["slow_shard"] = bench_slow_shard()
            result["flapping"] = bench_flapping()
            result["replication"] = bench_replication()
    except Exception as e:  # noqa: BLE001
        result = {"metric": "fault_tolerance",
                  "skipped": f"{type(e).__name__}: {e}"[:300]}
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
