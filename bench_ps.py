#!/usr/bin/env python3
"""PS hot-path benchmark: native async fan-out + read-parallel serving.

Run BY bench.py in a deadline-guarded child (same pattern as
bench_device.py); standalone `python bench_ps.py` works too.  Emits
BENCH_ps.json next to the BENCH_obs/BENCH_analysis series and prints ONE
JSON object.  Without the native core it degrades to {"skipped": ...}.

What it measures (all loopback, CPU shards):

  fanout        — ONE lookup batch whose ids span all shards, issued by
                  the sequential per-shard call loop vs the call_async
                  fan-out, at 1/4/8 shards.  Reports whole-batch mean/p99
                  latency + keys/s and the parallel/sequential latency
                  ratio — the fan-out's point is max(shard) vs
                  sum(shard), so the ratio should approach 1/shards.
  single_shard  — ONE shard hammered with Lookups by 1 vs 8 concurrent
                  client threads, served under the pre-PR mutex
                  (lock_mode="mutex") vs the read-parallel rwlock.
                  Reports keys/s each way and the rwlock/mutex ratio at
                  8 clients — reader parallelism is the whole difference.
  native_read   — the same 1/8-client hammer against the NATIVE Lookup
                  handler (PsShardServer(native_read=True): zero Python,
                  no GIL, no trampoline in the read loop) vs the Python
                  rwlock path.  native_over_python_8clients is the
                  headline: the rwlock path capped out at ~0.96x mutex
                  because request framing held the GIL; the native path
                  has no GIL to hold.
  write         — the WRITE-path mirror (--block write, run by bench.py
                  as the "ps_write" child): one native_read CPU shard
                  hammered with ApplyGrads by 1/4/8 writers through the
                  unary path (per-call write lock + whole-table snapshot
                  install) vs the server-side combiner (one
                  subtract.at + ONE install per drained batch) vs the
                  streaming push (framed deltas over one ordered
                  flow-controlled stream per writer, no per-call
                  dispatch), plus a device-shard fan-in cell counting
                  wasted optimistic-install scatter launches with and
                  without the combiner.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def bench_fanout(nshards: int, vocab: int = 65536, dim: int = 64,
                 batch: int = 4096, secs: float = 2.0) -> dict:
    from brpc_tpu.ps_remote import PsShardServer, RemoteEmbedding

    servers = [PsShardServer(vocab, dim, i, nshards)
               for i in range(nshards)]
    addrs = [s.address for s in servers]
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, batch).astype(np.int32)  # spans all shards
    out = {}
    try:
        for mode, parallel in (("sequential", False), ("parallel", True)):
            emb = RemoteEmbedding(addrs, vocab, dim, timeout_ms=60000,
                                  parallel=parallel)
            try:
                emb.lookup(ids)  # warm
                lat = []
                t_end = time.monotonic() + secs
                while time.monotonic() < t_end:
                    t0 = time.perf_counter_ns()
                    emb.lookup(ids)
                    lat.append((time.perf_counter_ns() - t0) / 1e6)
            finally:
                emb.close()
            lat.sort()
            mean_ms = sum(lat) / len(lat)
            out[mode] = {
                "mean_ms": round(mean_ms, 3),
                "p50_ms": round(_percentile(lat, 0.50), 3),
                "p99_ms": round(_percentile(lat, 0.99), 3),
                "keys_per_s": round(batch * 1000.0 / mean_ms, 0),
                "batches": len(lat),
            }
    finally:
        for s in servers:
            s.close()
    out["latency_ratio"] = round(
        out["parallel"]["mean_ms"] / out["sequential"]["mean_ms"], 3)
    return out


def bench_single_shard(clients: int, lock_mode: str, vocab: int = 65536,
                       dim: int = 128, batch: int = 2048,
                       secs: float = 2.0,
                       native_read: bool = False) -> dict:
    import struct

    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import PsShardServer

    server = PsShardServer(vocab, dim, 0, 1, lock_mode=lock_mode,
                           native_read=native_read)
    counts = [0] * clients
    stop = threading.Event()
    ready = threading.Barrier(clients + 1, timeout=30)

    def worker(i: int) -> None:
        ch = rpc.Channel(server.address, timeout_ms=60000)
        rng = np.random.default_rng(i)
        ids = rng.integers(0, vocab, batch).astype(np.int32)
        req = struct.pack("<i", batch) + ids.tobytes()
        try:
            ch.call("Ps", "Lookup", req)  # warm
            ready.wait()
            while not stop.is_set():
                ch.call("Ps", "Lookup", req)
                counts[i] += 1
        finally:
            ch.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    try:
        for t in threads:
            t.start()
        ready.wait()
        t0 = time.monotonic()
        time.sleep(secs)
        stop.set()
        for t in threads:
            t.join(30)
        dt = time.monotonic() - t0
        native_served = int(server.native_lookups)
    finally:
        stop.set()
        server.close()
    total = sum(counts)
    out = {
        "lookups_per_s": round(total / dt, 1),
        "keys_per_s": round(total * batch / dt, 0),
    }
    if native_read:
        out["native_lookups"] = native_served  # proves the path served
    return out


def bench_write_path(writers: int, mode: str, vocab: int = 32768,
                     dim: int = 64, batch: int = 64,
                     secs: float = 2.0) -> dict:
    """One native_read CPU shard hammered with ApplyGrads by `writers`
    concurrent threads.  mode: "unary" (per-call lock+install),
    "combined" (server-side GradCombiner: one subtract.at + one install
    per drained batch) or "stream" (framed deltas over one ordered
    flow-controlled stream per writer, feeding the combiner).  The
    elapsed window INCLUDES the stream drain (close+join = applied
    barrier), so keys/s is applied-throughput for every mode.

    Geometry is the big-table / small-delta regime (8MB shard, 64 keys
    per apply — production embedding shape): under native_read the unary
    write path pays a whole-table snapshot install PER CALL, which is
    exactly the cost the combiner amortizes across a drained batch."""
    import struct

    from brpc_tpu import rpc
    from brpc_tpu.ps_remote import PsShardServer

    server = PsShardServer(vocab, dim, 0, 1, native_read=True,
                           combine=(mode != "unary"),
                           stream=(mode == "stream"))
    counts = [0] * writers
    stop = threading.Event()
    ready = threading.Barrier(writers + 1, timeout=60)

    def worker(i: int) -> None:
        ch = rpc.Channel(server.address, timeout_ms=60000)
        rng = np.random.default_rng(i)
        ids = rng.integers(0, vocab, batch).astype(np.int32)
        grads = (rng.integers(-2, 3, (batch, dim))).astype(np.float32)
        req = struct.pack("<i", batch) + ids.tobytes() + grads.tobytes()
        try:
            if mode == "stream":
                st = ch.stream("Ps", "StreamApply")
                st.write(req)  # warm
                ready.wait()
                while not stop.is_set():
                    st.write(req)
                    counts[i] += 1
                st.close()
                st.join(timeout_s=120)
            else:
                ch.call("Ps", "ApplyGrad", req)  # warm
                ready.wait()
                while not stop.is_set():
                    ch.call("Ps", "ApplyGrad", req)
                    counts[i] += 1
        finally:
            ch.close()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(writers)]
    try:
        for t in threads:
            t.start()
        ready.wait()
        t0 = time.monotonic()
        time.sleep(secs)
        stop.set()
        for t in threads:
            t.join(180)
        # join AFTER the streams drained: applied throughput, not
        # buffered throughput
        dt = time.monotonic() - t0
    finally:
        stop.set()
        server.close()
    total = sum(counts)
    return {
        "applies_per_s": round(total / dt, 1),
        "keys_per_s": round(total * batch / dt, 0),
    }


def bench_device_write(writers: int, combine: bool, vocab: int = 8192,
                      dim: int = 64, batch: int = 256,
                      rounds: int = 15) -> dict:
    """Device-shard write fan-in: `writers` threads each apply `rounds`
    unary ApplyGrads.  Counts wasted optimistic-install scatter launches
    (lost-swap redos — ~linear in writers without the combiner) and, with
    the combiner, drained batches.  Uses the in-repo fake PJRT plugin;
    obs stays ON here because the counters ARE the metric."""
    import struct

    from brpc_tpu import obs, rpc
    from brpc_tpu.ps_remote import DevicePsShardServer

    fake = os.path.join(ROOT, "cpp", "build", "libbrt_fake_pjrt.so")
    plugin = os.environ.get("BRT_PJRT_PLUGIN") or fake
    dev = rpc.DeviceClient(plugin if os.path.exists(plugin) else None)
    obs.set_enabled(True)
    wasted0 = obs.counter("ps_device_wasted_launches").get_value()
    applies0 = obs.counter("ps_combined_applies").get_value()
    server = DevicePsShardServer(vocab, dim, 0, 1, device_client=dev,
                                 combine=combine)
    try:
        def worker(i: int) -> None:
            ch = rpc.Channel(server.address, timeout_ms=120000)
            rng = np.random.default_rng(i)
            ids = rng.integers(0, vocab, batch).astype(np.int32)
            grads = rng.standard_normal((batch, dim)).astype(np.float32)
            req = struct.pack("<i", batch) + ids.tobytes() + grads.tobytes()
            try:
                for _ in range(rounds):
                    ch.call("Ps", "ApplyGrad", req)
            finally:
                ch.close()

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(writers)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        dt = time.monotonic() - t0
    finally:
        server.close()
        dev.close()
    wasted = int(obs.counter("ps_device_wasted_launches").get_value()
                 - wasted0)
    batches = int(obs.counter("ps_combined_applies").get_value() - applies0)
    total = writers * rounds
    out = {
        "applies": total,
        "wasted_launches": wasted,
        "applies_per_s": round(total / dt, 1),
    }
    if combine:
        out["drained_batches"] = batches
        out["wasted_per_batch"] = round(wasted / max(batches, 1), 3)
    return out


def run_write_block() -> dict:
    """The ps_write bench.py child: unary vs combined vs stream applied
    throughput at 1/4/8 writers on one CPU shard, plus the device
    wasted-launch cell with/without the combiner."""
    from brpc_tpu import obs

    obs.set_enabled(False)  # throughput cells measure the fabric
    write: dict = {}
    for mode in ("unary", "combined", "stream"):
        write[mode] = {str(w): bench_write_path(w, mode)
                       for w in (1, 4, 8)}
    for key in ("combined", "stream"):
        write[f"{key}_over_unary_8writers"] = round(
            write[key]["8"]["keys_per_s"] /
            max(write["unary"]["8"]["keys_per_s"], 1.0), 3)
    try:
        device = {
            "unary": bench_device_write(8, combine=False),
            "combined": bench_device_write(8, combine=True),
        }
    except Exception as e:  # noqa: BLE001 — no plugin/device reachable
        device = {"skipped": f"{type(e).__name__}: {e}"[:200]}
    finally:
        obs.set_enabled(False)
    write["device_wasted_launches_8writers"] = device
    return write


def _merge_result(out_path: str, result: dict) -> None:
    """Keep the blocks the other --block run wrote (the hot and write
    children both land in BENCH_ps.json)."""
    try:
        with open(out_path) as f:
            old = json.load(f)
    except (OSError, ValueError):
        old = {}
    old.update(result)
    result.clear()
    result.update(old)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--block", choices=("all", "hot", "write"),
                        default="all",
                        help="hot = fanout/lock/native_read read-path "
                             "cells; write = combiner/stream write-path "
                             "cells")
    args = parser.parse_args()
    out_path = os.path.join(ROOT, "BENCH_ps.json")
    # cpu_count matters for reading the numbers: on a 1-core host there
    # is no idle time to overlap, so both ratios sit near 1.0 regardless
    # of implementation — the fan-out/rwlock wins show with cores.
    result: dict = {"metric": "ps_hot_path", "cpu_count": os.cpu_count()}
    # 8 concurrent handlers need >= 8 fiber workers regardless of host
    # size; must land before the first rpc call initializes the runtime.
    os.environ.setdefault("BRT_WORKERS", str(max(8, os.cpu_count() or 1)))
    try:
        from brpc_tpu import obs, rpc

        if not rpc.native_core_available():
            result = {"metric": "ps_hot_path",
                      "skipped": rpc._load_error or
                      "native core unavailable"}
        elif args.block == "write":
            result["write"] = run_write_block()
        else:
            obs.set_enabled(False)  # measure the fabric, not the meters
            result["fanout"] = {
                str(n): bench_fanout(n) for n in (1, 4, 8)}
            result["fanout_latency_ratio_4shards"] = \
                result["fanout"]["4"]["latency_ratio"]
            single = {}
            for lock_mode in ("mutex", "rw"):
                single[lock_mode] = {
                    str(c): bench_single_shard(c, lock_mode)
                    for c in (1, 8)}
            single["rw_over_mutex_8clients"] = round(
                single["rw"]["8"]["keys_per_s"] /
                max(single["mutex"]["8"]["keys_per_s"], 1.0), 3)
            result["single_shard_lookup"] = single
            # Native zero-Python read path vs the Python rwlock path.
            # Serving-style geometry (dim=16, batch=256 — the small
            # recommendation-lookup regime) so per-REQUEST overhead — the
            # GIL-held trampoline/framing the native path deletes — is
            # what gets measured, not response memcpy bandwidth; both
            # paths run the SAME geometry and client hammer.  On a 1-core
            # host this is the native path's WORST case (no handler
            # parallelism to win back), so the ratio is a floor.
            nr_kw = dict(dim=16, batch=256)

            def best_of(n, clients, native):
                # Shared 1-core hosts swing ~25% with neighbor noise
                # (same rationale as bench.py's best-of-3 headline):
                # noise only ever subtracts, so keep the best sample.
                return max((bench_single_shard(clients, "rw",
                                               native_read=native,
                                               **nr_kw)
                            for _ in range(n)),
                           key=lambda r: r["keys_per_s"])

            nat_block = {}
            for mode, native in (("python_rw", False), ("native", True)):
                nat_block[mode] = {
                    str(c): best_of(2, c, native) for c in (1, 8)}
            nat_block["native_over_python_8clients"] = round(
                nat_block["native"]["8"]["keys_per_s"] /
                max(nat_block["python_rw"]["8"]["keys_per_s"], 1.0), 3)
            result["native_read"] = nat_block
            if args.block == "all":
                result["write"] = run_write_block()
    except Exception as e:  # noqa: BLE001
        result = {"metric": "ps_hot_path",
                  "skipped": f"{type(e).__name__}: {e}"[:300]}
    if "skipped" not in result:
        _merge_result(out_path, result)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
